"""Lint driver: lower engine x model x mode combos on a virtual mesh
and run the rule registry over the compiled HLO.

`tools/hlolint` is the CLI; tests/test_hlolint.py runs a tier-1 subset
plus the full matrix (slow). Per-combo results stream as the
established partial-JSON convention (`{"leg": ..., "partial": true}`
lines, one per finished combo), so a wedged or killed run still shows
exactly which combos were judged; the final summary is one JSON object
with the violation count.

Heavy imports (jax, engines) are function-local: the registry and
parser stay importable without a backend, and the CLI can force the
CPU platform before anything dials a device.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from typing import Callable, List, Optional, Sequence, Tuple

from distributed_model_parallel_tpu.analysis.collectives import MeshModel
from distributed_model_parallel_tpu.analysis.rules import (
    Finding,
    LintContext,
    LintTarget,
    REGISTRY,
    run_rules,
)

_DTYPE_TOKEN = {
    "float32": "f32", "bfloat16": "bf16", "float16": "f16",
    "float64": "f64", "int8": "s8", "uint8": "u8",
}


@dataclasses.dataclass(frozen=True)
class Combo:
    """One cell of the engine x mode x mesh matrix. `size` is the
    engine's PRIMARY parallel axis: the data axis for dp/ddp/fsdp/sp_lm,
    'model' for tp, serve, and the cm_* op kernels, 'seq' for sp,
    'stage' for pipeline."""

    engine: str
    size: int
    grad_reduction: str = "monolithic"
    dcn: int = 1
    collective_matmul: bool = False
    bf16: bool = False
    model: str = "mlp"  # mlp | tinycnn (ddp/fsdp families)
    # MoE dispatch mode (engine == "ep"): "gspmd" = partitioner-chosen
    # flat exchange over 'expert'; "hierarchical" = the explicit
    # two-level moe_ring exchange over the (factored) data fabric,
    # "+ov" chunk-overlapped (`ops/expert_dispatch.py`).
    moe_dispatch: str = "gspmd"
    moe_overlap: bool = False

    # Cross-slice wire compression ("none" | "bf16" | "int8") — the
    # `dcn_compression` knob on the reducer engines / the hierarchical
    # MoE dispatch (`ops/wire_codec.py`, rule dcn-compressed-payload).
    dcn_compression: str = "none"

    # Tuner-searched reducer knobs (`tuning/`): an explicit bucket cap
    # (None = this module's BUCKET_MB, keeping every pre-existing combo
    # name and ledger row byte-stable) and an explicit stagewise
    # segment count (0 = the engines' auto default).
    bucket_mb: Optional[float] = None
    overlap_stages: int = 0

    # Paged serving knobs (engine == "serve", ISSUE 15): page_size
    # None keeps the PR 7 contiguous slot cache (every pre-existing
    # serve combo name and ledger row byte-stable); set = the
    # block-paged decode step. prefill_chunk shapes the HOST ingest
    # loop only (same compiled decode step) and rides the name for the
    # tuner's plan identity.
    page_size: Optional[int] = None
    prefill_chunk: int = 0

    # Quantized decode arithmetic (engine == "serve", ISSUE 16): None
    # keeps the f32 projections (every pre-existing serve combo name
    # and ledger row byte-stable); "bf16"/"int8" opt the decode
    # projection GEMMs into `ops/quant_matmul.py` (rule
    # decode-quantized-matmul).
    compute_dtype: Optional[str] = None

    # Speculative decoding (engine == "serve", ISSUE 18): 0 keeps the
    # plain decode step (every pre-existing serve combo name and
    # ledger row byte-stable); k > 0 lowers the VERIFY step instead —
    # the (slots, k+1) chunk-shaped pass rule spec-verify-step pins at
    # one decode step's ring inventory. Requires page_size (rollback
    # truncates the block table).
    speculative_k: int = 0

    # Composed ParallelPlan spec (engine == "plan", ISSUE 19): the
    # `parse_plan` spec string (e.g. "pp2xsp2xdp2", or the scheduled
    # "pp2-1f1bxdp4" / "pp2-int2xdp2" forms, ISSUE 20) the builder
    # lowers through ComposedPlanEngine. None everywhere else (every
    # pre-existing combo name and ledger row stays byte-stable).
    plan: Optional[str] = None

    # Pipeline fill depth for plan combos (ISSUE 20): 0 keeps the
    # engine default (M = pp * V — every pre-existing plan combo name
    # and ledger row byte-stable); set = the tuner's M knob, which the
    # bubble-factor compute fold (`cost.add_plan_compute`) prices.
    num_microbatches: int = 0

    @property
    def name(self) -> str:
        bits = [self.engine, f"S{self.size}"]
        if self.dcn > 1:
            bits.append(f"dcn{self.dcn}")
        if self.engine in ("ddp", "fsdp", "sp_lm"):
            bits.append(self.grad_reduction)
        if self.engine == "ep":
            bits.append(self.moe_dispatch)
            if self.moe_overlap:
                bits.append("ov")
        if self.plan is not None:
            bits.append(self.plan)
        if self.num_microbatches:
            bits.append(f"M{self.num_microbatches}")
        if self.dcn_compression != "none":
            bits.append(f"wire-{self.dcn_compression}")
        if self.bucket_mb is not None:
            bits.append(f"b{self.bucket_mb:g}")
        if self.overlap_stages:
            bits.append(f"seg{self.overlap_stages}")
        if self.page_size is not None:
            bits.append(f"pg{self.page_size}")
        if self.prefill_chunk:
            bits.append(f"ck{self.prefill_chunk}")
        if self.model != "mlp":
            bits.append(self.model)
        if self.collective_matmul:
            bits.append("cm")
        if self.bf16:
            bits.append("bf16")
        if self.compute_dtype is not None:
            bits.append(f"q-{self.compute_dtype}")
        if self.speculative_k:
            bits.append(f"spec{self.speculative_k}")
        return "/".join(bits)


@dataclasses.dataclass
class LintReport:
    combo: Combo
    target: LintTarget
    findings: List[Finding]
    n_collectives: int

    @property
    def violations(self) -> List[Finding]:
        return [f for f in self.findings if not f.exempted]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.violations if f.severity == "error"]


# ------------------------------------------------------------ models


def staged_mlp(n_blocks=8, width=32, classes=4):
    """BN-free stem/blocks/head MLP: no model_state, so the only
    data-fabric all-reduces an opted-in step may carry are the pinned
    bucket hops — the model the reducer rules are sharpest on. Public:
    tests/test_collectives_hlo.py pins against the SAME builder so the
    lint matrix and the HLO pin tests can never desynchronize."""
    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.models import staging

    stem = L.sequential(L.flatten(), L.linear(192, width), L.relu())
    blocks = [
        L.sequential(L.linear(width, width), L.relu())
        for _ in range(n_blocks)
    ]
    return staging.staged_model(stem, blocks, L.linear(width, classes))


def moe_classifier(num_experts: int, dim: int = 16, seq: int = 8,
                   num_classes: int = 4, top_k: int = 2,
                   capacity_factor: float = 1.25):
    """Tiny one-block MoE classifier (tokens (B, T, D) -> logits) —
    ONE routed layer so the moe_ring permute pin is exact. Public and
    imported by tests/test_expert_dispatch.py, so the lint matrix and
    the parity tests lower the SAME model (the staged_mlp/image_batch
    no-desync convention)."""
    import jax

    from distributed_model_parallel_tpu.models import layers as L
    from distributed_model_parallel_tpu.models.moe import (
        moe_encoder_layer,
    )

    block = moe_encoder_layer(
        dim, 2, 2 * dim, num_experts, top_k=top_k,
        capacity_factor=capacity_factor, dropout_rate=0.0,
    )
    head = L.linear(dim, num_classes)

    def init(key):
        kb, kh = jax.random.split(key)
        bp, bs = block.init(kb)
        return {"block": bp, "head": head.init(kh)[0]}, {"block": bs}

    def apply(params, state, x, ctx):
        (h, _), bs = block.apply(
            params["block"], state.get("block", {}), (x, None), ctx
        )
        logits, _ = head.apply(params["head"], {}, h.mean(axis=1), ctx)
        return logits, {"block": bs}

    return L.Layer(init, apply)


def _bert_cfg(model_size: int):
    from distributed_model_parallel_tpu.models.bert import BertConfig

    return BertConfig(
        vocab_size=64, hidden_size=32, num_layers=1,
        num_heads=max(2, model_size), intermediate_size=64,
        max_position=16, dropout_rate=0.0,
    )


def _gpt_cfg():
    from distributed_model_parallel_tpu.models.gpt import GPTConfig

    return GPTConfig(
        vocab_size=61, dim=16, num_layers=4, num_heads=2, ffn_dim=32,
        max_position=16, dropout_rate=0.0,
    )


def image_batch(n, hw=8, classes=4, seed=0):
    """Deterministic fake image batch (shared with the pin tests)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    return (
        rng.rand(n, hw, hw, 3).astype(np.float32),
        rng.randint(0, classes, size=(n,)).astype(np.int32),
    )


# ------------------------------------------------------- expectations


def _token(dtype) -> str:
    import numpy as np

    return _DTYPE_TOKEN.get(np.dtype(dtype).name, "f32")


def _bucket_plan(leaves, bucket_mb: float, pad_multiple: int):
    """[(padded_elems, dtype_token)] for one segment's gradient tree —
    the shape the per-bucket collectives are pinned against.
    `pad_multiple` comes from `grad_reduction.bucket_pad_multiple` (the
    ici ring size, times the dcn factor on compressed combos)."""
    from distributed_model_parallel_tpu.ops.grad_reduction import (
        plan_buckets,
    )

    out = []
    for b in plan_buckets(leaves, bucket_mb):
        padded = b.size + (-b.size % pad_multiple)
        out.append((padded, _token(b.dtype)))
    return tuple(out)


def _reducer_plans(model, grad_reduction: str, bucket_mb: float,
                   ici_size: int, dcn_size: int = 1,
                   dcn_compression: str = "none",
                   overlap_stages: int = 0):
    """Per-segment bucket plans + segment count for a staged model —
    one segment for 'bucketed', split_points segments for
    'overlapped', one WHOLE-TREE bucket per dtype for compressed
    'monolithic' (the engines' single-flat-bucket path). Empty for
    uncompressed 'monolithic'."""
    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.models import staging
    from distributed_model_parallel_tpu.ops.grad_reduction import (
        MONOLITHIC_BUCKET_MB,
        bucket_pad_multiple,
    )

    pad_mult = bucket_pad_multiple(ici_size, dcn_size, dcn_compression)
    key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
    p_aval, s_aval = jax.eval_shape(model.init, key_aval)
    state_shapes = tuple(
        tuple(leaf.shape)
        for leaf in jax.tree_util.tree_leaves(s_aval)
    )
    if grad_reduction == "monolithic" and dcn_compression != "none":
        plans = (_bucket_plan(
            jax.tree_util.tree_leaves(p_aval), MONOLITHIC_BUCKET_MB,
            pad_mult,
        ),)
        return plans, 0, state_shapes
    if grad_reduction == "bucketed":
        plans = (_bucket_plan(
            jax.tree_util.tree_leaves(p_aval), bucket_mb, pad_mult
        ),)
        return plans, 0, state_shapes
    if grad_reduction == "overlapped":
        n = staging.resolve_overlap_segments(
            len(model.parts.blocks), overlap_stages, "lint"
        )
        cuts = staging.split_points(n, None, len(model.parts.blocks))
        plans = tuple(
            _bucket_plan(
                jax.tree_util.tree_leaves(sp), bucket_mb, pad_mult
            )
            for sp in staging.partition_tree(p_aval, cuts)
        )
        return plans, n, state_shapes
    return (), 0, state_shapes


def _wire_chunk_expectations(plans, ici_size: int, dcn_size: int,
                             dcn_compression: str):
    """Expected (elems, wire_dtype_token) multiset of the compressed
    'dcn' payload hops: each bucket's 1/ici shard re-chunks across the
    K slices and crosses 2(K-1) times (exchange + gather,
    `grad_reduction.compressed_dcn_psum`)."""
    if dcn_compression == "none" or dcn_size <= 1:
        return ()
    from distributed_model_parallel_tpu.analysis.rules import (
        DCN_WIRE_TOKEN,
    )

    # Every payload hop carries the WIRE dtype regardless of the
    # bucket's math dtype (wire_encode casts unconditionally).
    wire = DCN_WIRE_TOKEN[dcn_compression]
    chunks = []
    for plan in plans:
        for padded, _dt in plan:
            nl = padded // (ici_size * dcn_size)
            chunks += [(nl, wire)] * (2 * (dcn_size - 1))
    return tuple(chunks)


def _fsdp_gather_chunk_expectations(
    full_leaf_shapes, dcn_size: int, dcn_compression: str,
    gathers_per_leaf: int,
):
    """Expected (n_elems, wire_dtype) multiset of FSDP's compressed
    WEIGHT-gather ring hops (ISSUE 16 satellite,
    `parallel/fsdp._coded_dcn_gather`): each dcn-crossing leaf crosses
    'dcn' in (K-1) coded hops of full_leaf/K elems per gather —
    `gathers_per_leaf` is 1 for the single-entry steps, 2 under
    "overlapped" (forward gather + backward regather)."""
    if dcn_compression == "none" or dcn_size <= 1:
        return ()
    import math as _math

    from distributed_model_parallel_tpu.analysis.rules import (
        DCN_WIRE_TOKEN,
    )

    wire = DCN_WIRE_TOKEN[dcn_compression]
    chunks = []
    for shape in full_leaf_shapes:
        hop = _math.prod(shape) // dcn_size
        chunks += [(hop, wire)] * ((dcn_size - 1) * gathers_per_leaf)
    return tuple(chunks)


def _n_param_leaves(ts) -> int:
    import jax

    return len(jax.tree_util.tree_leaves(ts.params)) + len(
        jax.tree_util.tree_leaves(ts.opt_state)
    )


def jaxpr_ppermute_records(fn, *args):
    """((axis_names, dtype_token, scope, n_elems), ...) for every
    `ppermute` equation in fn's jaxpr, sub-jaxprs included — the
    trace-level record the bf16-ring and compressed-wire rules read
    (compiled CPU HLO normalizes bf16 collectives to f32, so dtype
    contracts must come from the trace). `scope` is the equation's
    name_stack string (named_scope names survive jvp and transpose,
    e.g. 'transpose(jvp(kv_ring))'), which is how the rules
    distinguish the deliberately-f32 KV ring from the cm rings and a
    `dcn_wire` payload hop from its `dcn_scale` sidecar."""
    import math as _math

    import jax

    closed = jax.make_jaxpr(fn)(*args)
    out = []
    seen = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "ppermute":
                axes = eqn.params.get("axis_name")
                axes = axes if isinstance(axes, tuple) else (axes,)
                aval = eqn.invars[0].aval
                dt = str(aval.dtype)
                out.append((
                    tuple(str(a) for a in axes),
                    _DTYPE_TOKEN.get(dt, dt),
                    str(eqn.source_info.name_stack),
                    int(_math.prod(aval.shape)) if aval.shape else 1,
                ))
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub)

    def _subjaxprs(v):
        import jax.core as core

        if isinstance(v, core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                yield from _subjaxprs(x)

    walk(closed.jaxpr)
    return tuple(out)


# Named-axis collectives the plan fabric rules read, with the eqn
# param their axis names live under (ppermute-family primitives carry
# `axis_name`; the reduction family carries `axes`, possibly mixed
# with positional ints which are not named-axis traffic).
_COLLECTIVE_AXIS_PARAM = {
    "ppermute": "axis_name",
    "all_gather": "axis_name",
    "all_to_all": "axis_name",
    "reduce_scatter": "axis_name",
    "psum": "axes",
    "pmax": "axes",
    "pmin": "axes",
}


def jaxpr_collective_records(fn, *args):
    """((primitive, axis_names, dtype_token, scope, n_elems), ...) for
    every named-axis collective equation in fn's jaxpr, sub-jaxprs
    included — the multi-primitive generalization of
    `jaxpr_ppermute_records` the composed-plan fabric rules read
    (`LintTarget.plan_collective_records`): compiled HLO flattens axis
    names to replica groups and normalizes dtypes, so an axis->fabric
    contract must be pinned at trace level. Positional (int) axes are
    dropped from the record — they are intra-shard reductions, not
    fabric traffic."""
    import math as _math

    import jax

    closed = jax.make_jaxpr(fn)(*args)
    out = []
    seen = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            key = _COLLECTIVE_AXIS_PARAM.get(eqn.primitive.name)
            if key is not None:
                axes = eqn.params.get(key)
                axes = axes if isinstance(axes, tuple) else (axes,)
                names = tuple(
                    str(a) for a in axes if isinstance(a, str)
                )
                if names:
                    aval = eqn.invars[0].aval
                    dt = str(aval.dtype)
                    n_elems = sum(
                        int(_math.prod(v.aval.shape))
                        if v.aval.shape else 1
                        for v in eqn.invars
                        if hasattr(v.aval, "shape")
                    )
                    out.append((
                        eqn.primitive.name,
                        names,
                        _DTYPE_TOKEN.get(dt, dt),
                        str(eqn.source_info.name_stack),
                        n_elems,
                    ))
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub)

    def _subjaxprs(v):
        import jax.core as core

        if isinstance(v, core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                yield from _subjaxprs(x)

    walk(closed.jaxpr)
    return tuple(out)


def jaxpr_ppermute_dtypes(fn, *args):
    """The (axis_names, dtype_token, scope) cut of
    `jaxpr_ppermute_records` — the record shape `LintTarget.ring_dtypes`
    carries for the bf16-ring-upcast rule."""
    return tuple(r[:3] for r in jaxpr_ppermute_records(fn, *args))


def jaxpr_dot_records(fn, *args):
    """((lhs_dtype_token, rhs_dtype_token, rhs_shape), ...) for every
    `dot_general` equation in fn's jaxpr, sub-jaxprs (pjit bodies,
    shard_map fold bodies) included — the quant twin of
    `jaxpr_ppermute_records`. Compiled CPU HLO normalizes int8/bf16
    dots back to f32, so the `decode-quantized-matmul` rule pins the
    compute-dtype contract from these trace-level records
    (`LintTarget.decode_dot_records`)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    out = []
    seen = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dot_general":
                lhs = str(eqn.invars[0].aval.dtype)
                rhs = str(eqn.invars[1].aval.dtype)
                out.append((
                    _DTYPE_TOKEN.get(lhs, lhs),
                    _DTYPE_TOKEN.get(rhs, rhs),
                    tuple(int(d) for d in eqn.invars[1].aval.shape),
                ))
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub)

    def _subjaxprs(v):
        import jax.core as core

        if isinstance(v, core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                yield from _subjaxprs(x)

    walk(closed.jaxpr)
    return tuple(out)


def _mesh_facts(mesh):
    from distributed_model_parallel_tpu.runtime.mesh import (
        data_hierarchy_axes,
    )

    d_axes, ici_axis, dcn_axis = data_hierarchy_axes(mesh)
    return dict(
        data_axes=tuple(d_axes),
        ici_axis=ici_axis,
        dcn_axis=dcn_axis,
        ici_size=int(mesh.shape[ici_axis]),
        dcn_size=int(mesh.shape[dcn_axis]) if dcn_axis else 1,
    )


# ----------------------------------------------------------- builders

BUCKET_MB = 0.02  # small enough that every lint model splits >1 bucket


def _build_data_engine(combo: Combo, devices):
    """ddp / fsdp / dp over a data(-factored) mesh."""
    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    s = combo.size
    mesh = make_mesh(
        MeshSpec(data=s, dcn=combo.dcn), devices=devices[:s]
    )
    facts = _mesh_facts(mesh)
    if combo.model == "tinycnn":
        model = tiny_cnn(4)
    else:
        model = staged_mlp(width=128 if combo.engine == "fsdp" else 32)
    cdt = jnp.bfloat16 if combo.bf16 else None
    kwargs = dict(donate=True, compute_dtype=cdt)
    bmb = BUCKET_MB if combo.bucket_mb is None else combo.bucket_mb
    full_leaf_shapes: Tuple = ()
    if combo.engine == "dp":
        from distributed_model_parallel_tpu.parallel.data_parallel import (
            DataParallelEngine,
        )

        eng = DataParallelEngine(model, SGD(), mesh, **kwargs)
    elif combo.engine == "ddp":
        from distributed_model_parallel_tpu.parallel.data_parallel import (
            DDPEngine,
        )

        eng = DDPEngine(
            model, SGD(), mesh, grad_reduction=combo.grad_reduction,
            bucket_mb=bmb, overlap_stages=combo.overlap_stages,
            dcn_compression=combo.dcn_compression, **kwargs,
        )
    else:  # fsdp
        from distributed_model_parallel_tpu.parallel.fsdp import (
            FSDPEngine, fsdp_specs,
        )
        from distributed_model_parallel_tpu.runtime.mesh import (
            data_axis_names, data_axis_size,
        )

        min_elems = 64
        eng = FSDPEngine(
            model, SGD(), mesh, min_shard_elems=min_elems,
            grad_reduction=combo.grad_reduction, bucket_mb=bmb,
            overlap_stages=combo.overlap_stages,
            dcn_compression=combo.dcn_compression, **kwargs,
        )
        from jax.sharding import PartitionSpec as P

        key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
        p_aval, _ = jax.eval_shape(model.init, key_aval)
        specs = fsdp_specs(
            p_aval, data_axis_size(mesh), min_shard_elems=min_elems,
            axes=data_axis_names(mesh),
        )
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        shapes = []
        for leaf, spec in zip(
            jax.tree_util.tree_leaves(p_aval),
            jax.tree_util.tree_leaves(specs, is_leaf=is_spec),
        ):
            if any(part is not None for part in spec):
                shapes.append(tuple(leaf.shape))
        full_leaf_shapes = tuple(shapes)

    plans, n_seg, state_shapes = _reducer_plans(
        model, combo.grad_reduction, bmb, facts["ici_size"],
        facts["dcn_size"], combo.dcn_compression,
        combo.overlap_stages,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    im, lb = eng.shard_batch(*image_batch(16 * (s // 2 or 1)))
    hlo = eng.train_step.lower(
        ts, im, lb, jnp.float32(0.1)
    ).compile().as_text()
    dcn_records = (
        jaxpr_ppermute_records(eng.train_step, ts, im, lb,
                               jnp.float32(0.1))
        if combo.dcn_compression != "none" else ()
    )
    target = LintTarget(
        name=combo.name, engine=combo.engine,
        grad_reduction=combo.grad_reduction, bf16=combo.bf16,
        donate=True, bucket_plans=plans, overlap_segments=n_seg,
        state_leaf_shapes=state_shapes,
        fsdp_full_leaf_shapes=full_leaf_shapes,
        dcn_compression=combo.dcn_compression,
        dcn_wire_chunks=_wire_chunk_expectations(
            plans, facts["ici_size"], facts["dcn_size"],
            combo.dcn_compression,
        ),
        dcn_gather_chunks=_fsdp_gather_chunk_expectations(
            full_leaf_shapes, facts["dcn_size"],
            combo.dcn_compression,
            2 if combo.grad_reduction == "overlapped" else 1,
        ),
        dcn_ring_records=dcn_records,
        n_param_leaves=_n_param_leaves(ts), **facts,
    )
    return target, hlo, mesh


def _build_tp(combo: Combo, devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.models.bert import (
        bert_for_classification,
    )
    from distributed_model_parallel_tpu.parallel.tensor_parallel import (
        TensorParallelEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    s = combo.size
    dp = 2 if 2 * s <= len(devices) else 1
    mesh = make_mesh(
        MeshSpec(data=dp, model=s), devices=devices[: dp * s]
    )
    cfg = _bert_cfg(s)
    eng = TensorParallelEngine(
        bert_for_classification(4, cfg), SGD(), mesh, donate=True,
        collective_matmul=combo.collective_matmul,
        compute_dtype=jnp.bfloat16 if combo.bf16 else None,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 64, size=(4 * dp, 8)).astype(np.int32)
    lb = rng.randint(0, 4, size=(4 * dp,)).astype(np.int32)
    ids, lb = eng.shard_batch(ids, lb)
    hlo = eng.train_step.lower(
        ts, ids, lb, jnp.float32(0.1)
    ).compile().as_text()
    ring_dtypes = (
        jaxpr_ppermute_dtypes(eng.train_step, ts, ids, lb,
                              jnp.float32(0.1))
        if combo.bf16 else ()
    )
    target = LintTarget(
        name=combo.name, engine="tp", donate=True, bf16=combo.bf16,
        ring_dtypes=ring_dtypes,
        collective_matmul=combo.collective_matmul,
        cm_axis="model" if combo.collective_matmul else None,
        cm_size=s,
        # 1 block = 4 opted-in projections; fwd 4(S-1) rings + the
        # custom-vjp dual kernels >= 6(S-1) more (PR 2's engine pin).
        cm_min_ring_permutes=10 * (s - 1),
        n_param_leaves=_n_param_leaves(ts), **_mesh_facts(mesh),
    )
    return target, hlo, mesh


def _build_sp(combo: Combo, devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        SequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    s = combo.size
    dp = 2 if 2 * s <= len(devices) else 1
    mesh = make_mesh(
        MeshSpec(data=dp, seq=s), devices=devices[: dp * s]
    )
    cfg = _bert_cfg(4)
    eng = SequenceParallelEngine(
        cfg, 4, SGD(), mesh, donate=True,
        collective_matmul=combo.collective_matmul,
        compute_dtype=jnp.bfloat16 if combo.bf16 else None,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 64, size=(4 * dp, 16)).astype(np.int32)
    lb = rng.randint(0, 4, size=(4 * dp,)).astype(np.int32)
    ids, lb = eng.shard_batch(ids, lb)
    hlo = eng.train_step.lower(
        ts, ids, lb, jnp.float32(0.1)
    ).compile().as_text()
    ring_dtypes = (
        jaxpr_ppermute_dtypes(eng.train_step, ts, ids, lb,
                              jnp.float32(0.1))
        if combo.bf16 else ()
    )
    target = LintTarget(
        name=combo.name, engine="sp", donate=True, bf16=combo.bf16,
        ring_dtypes=ring_dtypes,
        collective_matmul=combo.collective_matmul,
        cm_axis="seq" if combo.collective_matmul else None,
        cm_size=s,
        # 1 block's FFN pair per step: fwd 2(S-1) rings + dual-kernel
        # bwd 3(S-1) rings = 5(S-1) hops (PR 2's kernel accounting);
        # the KV ring's hops ride the same axis, so this is a floor.
        cm_min_ring_permutes=5 * (s - 1),
        n_param_leaves=_n_param_leaves(ts), **_mesh_facts(mesh),
    )
    return target, hlo, mesh


def _build_sp_lm(combo: Combo, devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.models.gpt import gpt_lm
    from distributed_model_parallel_tpu.parallel.sequence_parallel import (
        CausalLMSequenceParallelEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    s = combo.size  # the DATA axis (the bucket rings' fabric)
    seq = 2
    mesh = make_mesh(
        MeshSpec(data=s, seq=seq, dcn=combo.dcn),
        devices=devices[: s * seq],
    )
    facts = _mesh_facts(mesh)
    cfg = _gpt_cfg()
    bmb = BUCKET_MB if combo.bucket_mb is None else combo.bucket_mb
    eng = CausalLMSequenceParallelEngine(
        cfg, SGD(), mesh, donate=True,
        grad_reduction=combo.grad_reduction, bucket_mb=bmb,
        overlap_stages=combo.overlap_stages,
        collective_matmul=combo.collective_matmul,
        dcn_compression=combo.dcn_compression,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 61, size=(4 * s, 16)).astype(np.int32)
    ids, tg = eng.shard_batch(ids)
    hlo = eng.train_step.lower(
        ts, ids, tg, jnp.float32(0.1)
    ).compile().as_text()

    # Reducer expectations over the LM's stem/blocks/head params —
    # gpt_lm builds through the staged substrate, so the shared
    # expectation builder serves it like the image engines (one copy
    # of the monolithic-compressed/bucketed/overlapped plan logic).
    plans, n_seg, _ = _reducer_plans(
        gpt_lm(cfg), combo.grad_reduction, bmb,
        facts["ici_size"], facts["dcn_size"], combo.dcn_compression,
        combo.overlap_stages,
    )
    dcn_records = (
        jaxpr_ppermute_records(eng.train_step, ts, ids, tg,
                               jnp.float32(0.1))
        if combo.dcn_compression != "none" else ()
    )
    target = LintTarget(
        name=combo.name, engine="sp_lm",
        grad_reduction=combo.grad_reduction, donate=True,
        collective_matmul=combo.collective_matmul,
        cm_axis="seq" if combo.collective_matmul else None,
        cm_size=seq,
        cm_min_ring_permutes=5 * (seq - 1) * cfg.num_layers,
        bucket_plans=plans, overlap_segments=n_seg,
        dcn_compression=combo.dcn_compression,
        dcn_wire_chunks=_wire_chunk_expectations(
            plans, facts["ici_size"], facts["dcn_size"],
            combo.dcn_compression,
        ),
        dcn_ring_records=dcn_records,
        n_param_leaves=_n_param_leaves(ts), **facts,
    )
    return target, hlo, mesh


def _build_ep(combo: Combo, devices):
    """MoE expert-parallel train steps (`parallel/expert_parallel.py`).
    `moe_dispatch="gspmd"`: the original 'expert'-axis layout on a
    (data=2, expert=S) mesh, judged by the generic rules only.
    `moe_dispatch="hierarchical"` (+overlap): the explicit two-level
    exchange over a (data=S[, dcn]) fabric — rule `moe-hierarchical-
    a2a` pins the exact moe_ring chain and the absence of any flat
    all-to-all on the data axes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.ops.expert_dispatch import (
        exchange_permutes,
    )
    from distributed_model_parallel_tpu.parallel.expert_parallel import (
        ExpertParallelEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    s = combo.size
    dim, seq = 16, 8
    if combo.moe_dispatch == "hierarchical":
        mesh = make_mesh(
            MeshSpec(data=s, dcn=combo.dcn), devices=devices[:s]
        )
        eng = ExpertParallelEngine(
            moe_classifier(s, dim=dim), SGD(), mesh, donate=True,
            dispatch="hierarchical", overlap=combo.moe_overlap,
            dcn_compression=combo.dcn_compression,
        )
        facts = _mesh_facts(mesh)
        # One MoE layer, fwd exchange pair + mirrored backward.
        expected = 2 * exchange_permutes(
            facts["ici_size"], facts["dcn_size"]
        )
    else:
        dp = 2 if 2 * s <= len(devices) else 1
        mesh = make_mesh(
            MeshSpec(data=dp, expert=s), devices=devices[: dp * s]
        )
        eng = ExpertParallelEngine(
            moe_classifier(s, dim=dim), SGD(), mesh, donate=True
        )
        facts = _mesh_facts(mesh)
        expected = None
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    n = max(8, int(mesh.shape[facts["ici_axis"]]) * facts["dcn_size"])
    x = rng.randn(n, seq, dim).astype(np.float32)
    lb = rng.randint(0, 4, size=(n,)).astype(np.int32)
    xs, lbs = eng.shard_batch(x, lb)
    hlo = eng.train_step.lower(
        ts, xs, lbs, jnp.float32(0.1)
    ).compile().as_text()
    # Compressed exchange: per routed layer the 'dcn' stage crosses
    # 2(K-1) hops per direction pair (dispatch + combine, or the
    # overlapped ring's in+out), doubled by the mirrored backward =
    # 4(K-1) dcn_wire payload hops (one routed layer here). The chunk
    # SHAPES are model-dependent, so the rule pins hop count + wire
    # dtype (`dcn_wire_hops`) instead of a byte multiset.
    wire_hops = None
    dcn_records = ()
    if combo.dcn_compression != "none":
        wire_hops = 4 * (facts["dcn_size"] - 1)
        dcn_records = jaxpr_ppermute_records(
            eng.train_step, ts, xs, lbs, jnp.float32(0.1)
        )
    target = LintTarget(
        name=combo.name, engine="ep", donate=True,
        moe_dispatch=combo.moe_dispatch,
        moe_ring_permutes=expected,
        dcn_compression=combo.dcn_compression,
        dcn_wire_hops=wire_hops,
        dcn_ring_records=dcn_records,
        n_param_leaves=_n_param_leaves(ts), **facts,
    )
    return target, hlo, mesh


def _build_pipeline(combo: Combo, devices):
    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.models.tinycnn import split_stages
    from distributed_model_parallel_tpu.parallel.pipeline import (
        PipelineEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    s = combo.size
    dp = max(1, len(devices) // s)
    mesh = make_mesh(
        MeshSpec(data=dp, stage=s), devices=devices[: dp * s]
    )
    eng = PipelineEngine(
        split_stages(s, 4), SGD(), mesh, num_microbatches=2,
        donate=True,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    im, lb = eng.shard_batch(*image_batch(4 * dp))
    hlo = eng.train_step.lower(
        ts, im, lb, jnp.float32(0.1)
    ).compile().as_text()
    target = LintTarget(
        name=combo.name, engine="pipeline", donate=True,
        n_param_leaves=_n_param_leaves(ts), **_mesh_facts(mesh),
    )
    return target, hlo, mesh


def _build_cm_op(combo: Combo, devices):
    """Op-level kernel targets: the exact S-1 pin on ag_matmul /
    matmul_rs, matching PR 2's kernel tests."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from distributed_model_parallel_tpu.ops.collective_matmul import (
        ag_matmul, matmul_rs,
    )
    from distributed_model_parallel_tpu.runtime.compat import shard_map

    s = combo.size
    mesh = Mesh(np.array(devices[:s]), ("model",))
    dt = jnp.bfloat16 if combo.bf16 else jnp.float32
    if combo.engine == "cm_ag":
        x = jnp.zeros((2, 4 * s, 16), dt)
        w = jnp.zeros((16, 8 * s), dt)
        fn = jax.jit(shard_map(
            partial(ag_matmul, axis_name="model"), mesh=mesh,
            in_specs=(P(None, "model", None), P(None, "model")),
            out_specs=P(None, None, "model"), check_vma=False,
        ))
    else:
        x = jnp.zeros((2, 4 * s, 8 * s), dt)
        w = jnp.zeros((8 * s, 16), dt)
        fn = jax.jit(shard_map(
            partial(matmul_rs, axis_name="model"), mesh=mesh,
            in_specs=(P(None, None, "model"), P("model", None)),
            out_specs=P(None, "model", None), check_vma=False,
        ))
    hlo = fn.lower(x, w).compile().as_text()
    target = LintTarget(
        name=combo.name, engine=combo.engine, bf16=combo.bf16,
        data_axes=(), ici_axis=None, ici_size=1,
        cm_axis="model", cm_size=s, expected_permutes=s - 1,
    )
    return target, hlo, mesh


def _build_serve(combo: Combo, devices):
    """Serving decode-step targets (`serving/engine.py`, tp layout):
    the jitted mixed-position token step over the slot-paged KV cache,
    declarative or with the opted-in decode rings. The lint pins the
    PR 7 contract: an opted-in step carries exactly 4*L*(S-1)
    `serve_ring`-tagged permutes and no monolithic all-gather over
    'model' (rule `serve-decode-ring`)."""
    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec, make_mesh,
    )
    from distributed_model_parallel_tpu.serving.decode import (
        decode_ring_permutes,
    )
    from distributed_model_parallel_tpu.serving.engine import (
        ServingEngine,
    )

    s = combo.size
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    cfg = GPTConfig(
        vocab_size=61, dim=16, num_layers=2, num_heads=4, ffn_dim=32,
        max_position=16, dropout_rate=0.0,
    )
    eng = ServingEngine(
        cfg, mesh, layout="tp", num_slots=2 * s, max_len=16,
        prefill_len=8, collective_matmul=combo.collective_matmul,
        compute_dtype=(
            combo.compute_dtype
            or (jnp.bfloat16 if combo.bf16 else None)
        ),
        page_size=combo.page_size,
        speculative_k=combo.speculative_k,
    )
    params = eng.init_params(jax.random.PRNGKey(0))
    cache = eng.init_cache()
    tokens = jnp.zeros((eng.num_slots,), jnp.int32)
    active = jnp.ones((eng.num_slots,), jnp.bool_)
    if combo.speculative_k:
        # The VERIFY step (ISSUE 18): scores k+1 positions per slot in
        # one chunk-shaped pass. Rule spec-verify-step pins its ring
        # inventory at ONE decode step's — the chunk axis must ride
        # the rings' local operand, never the fabric.
        host = eng.new_host()
        for slot in range(eng.num_slots):
            host.ensure_pages(slot, 8 + combo.speculative_k + 1)
        positions = jnp.full((eng.num_slots,), 8, jnp.int32)
        tokens_chunk = jnp.zeros(
            (eng.num_slots, combo.speculative_k + 1), jnp.int32
        )
        step_args = (
            params, cache, host.device_table(), positions,
            tokens_chunk, active,
        )
        expected = (
            decode_ring_permutes(cfg.num_layers, s)
            if combo.collective_matmul else None
        )
        hlo = eng.verify_step.lower(*step_args).compile().as_text()
        target = LintTarget(
            name=combo.name, engine="serve", donate=True,
            bf16=combo.bf16,
            collective_matmul=combo.collective_matmul,
            cm_axis="model" if combo.collective_matmul else None,
            cm_size=s,
            cm_min_ring_permutes=expected or 0,
            speculative_k=combo.speculative_k,
            spec_verify_permutes=expected,
            n_param_leaves=2,  # the paged cache donates {k, v}
            **_mesh_facts(mesh),
        )
        return target, hlo, mesh
    if combo.page_size is not None:
        # The paged step: block-table gathers/scatters are LOCAL
        # indexing ops, so the decode collective inventory — and
        # therefore every rule expectation below — must be identical
        # to the contiguous step's (the acceptance pin: paging never
        # buys memory with extra wire traffic).
        host = eng.new_host()
        for slot in range(eng.num_slots):
            host.ensure_pages(slot, 8)
        positions = jnp.full((eng.num_slots,), 8, jnp.int32)
        step_args = (
            params, cache, host.device_table(), positions, tokens,
            active,
        )
        n_donated = 2  # the paged cache donates {k, v}
    else:
        step_args = (params, cache, tokens, active)
        n_donated = 3  # {k, v, lengths}
    hlo = eng.decode_step.lower(*step_args).compile().as_text()
    expected = (
        decode_ring_permutes(cfg.num_layers, s)
        if combo.collective_matmul else None
    )
    # Quantized-decode expectations (rule decode-quantized-matmul):
    # trace-level dot records, since compiled CPU HLO normalizes the
    # int8/bf16 dots back to f32. 4 opted-in projections per block,
    # each lowering to S chunk dots under the rings (1 declaratively).
    dot_records = (
        jaxpr_dot_records(eng.decode_step, *step_args)
        if combo.compute_dtype else ()
    )
    quant_dots = (
        4 * cfg.num_layers * (s if combo.collective_matmul else 1)
        if combo.compute_dtype else None
    )
    target = LintTarget(
        name=combo.name, engine="serve", donate=True, bf16=combo.bf16,
        collective_matmul=combo.collective_matmul,
        cm_axis="model" if combo.collective_matmul else None,
        cm_size=s,
        # Floor for the shared cm-ring-permutes rule (GSPMD adds its
        # own resharding permutes on top); the exact tagged pin is
        # serve-decode-ring's.
        cm_min_ring_permutes=expected or 0,
        serve_decode_permutes=expected,
        # The decode step donates the cache leaves.
        n_param_leaves=n_donated,
        compute_dtype=combo.compute_dtype,
        decode_dot_records=dot_records,
        quant_dot_count=quant_dots,
        head_weight_shape=(cfg.dim, cfg.vocab_size),
        **_mesh_facts(mesh),
    )
    return target, hlo, mesh


def _build_plan(combo: Combo, devices):
    """Composed-ParallelPlan train steps (`parallel/plan.py`, ISSUE
    19) on the stage-major ('stage', 'data', 'seq') plan mesh. The
    three plan-* fabric rules read `plan_collective_records` — the
    trace-level inventory from `jaxpr_collective_records` — because
    every contract here is a named-axis one: the plan_wire ppermute
    rides ('stage',), the kv_ring/cm rings ride ('seq',), and the
    fused plan_grad psum spans all three axes in one rendezvous."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_model_parallel_tpu.parallel.plan import (
        ComposedPlanEngine, parse_plan,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        make_plan_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD

    plan = parse_plan(combo.plan)
    if plan.num_devices != combo.size:
        raise ValueError(
            f"combo size {combo.size} != plan {plan.spec!r} device "
            f"count {plan.num_devices}"
        )
    mesh = make_plan_mesh(
        plan.pp, plan.dp, plan.tp_or_sp,
        devices=devices[: plan.num_devices],
    )
    cfg = _gpt_cfg()
    chunks = plan.pp * plan.virtual_stages
    if cfg.num_layers % chunks:
        # Deep-pipeline specs (pp8 at S8) and interleaved ones need a
        # chunk-divisible stack; widen the proxy to pp*V layers — the
        # same proxy-fits-the-grid compromise as space._BUCKET_GRID's
        # sub-MB values. `cost.plan_combo_compute_s` mirrors this.
        import dataclasses as _dc

        cfg = _dc.replace(cfg, num_layers=chunks)
    eng = ComposedPlanEngine(
        cfg, SGD(), mesh, plan, min_shard_elems=64,
        num_microbatches=combo.num_microbatches or None,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(
        1, 61, size=(4 * plan.dp * plan.pp, 16)
    ).astype(np.int32)
    ids, tg = eng.shard_batch(ids)
    hlo = eng.train_step.lower(
        ts, ids, tg, jnp.float32(0.1)
    ).compile().as_text()
    records = jaxpr_collective_records(
        eng.train_step, ts, ids, tg, jnp.float32(0.1)
    )
    target = LintTarget(
        name=combo.name, engine="plan", donate=True,
        plan_axes=(
            ("stage", plan.pp), ("data", plan.dp),
            ("seq", plan.tp_or_sp),
        ),
        plan_collective_records=records,
        plan_schedule=plan.schedule,
        plan_virtual=plan.virtual_stages,
        n_param_leaves=_n_param_leaves(ts),
        **_mesh_facts(mesh),
    )
    return target, hlo, mesh


_BUILDERS: dict = {
    "dp": _build_data_engine,
    "ddp": _build_data_engine,
    "fsdp": _build_data_engine,
    "tp": _build_tp,
    "sp": _build_sp,
    "sp_lm": _build_sp_lm,
    "pipeline": _build_pipeline,
    "cm_ag": _build_cm_op,
    "cm_rs": _build_cm_op,
    "serve": _build_serve,
    "ep": _build_ep,
    "plan": _build_plan,
}


def lower_combo(combo: Combo, devices=None):
    """Lower one combo through its builder: (LintTarget, compiled HLO
    text, mesh). Shared by the rule driver (`lint_combo`) and the cost
    engine (`observability/cost.combo_cost`) so both judge the SAME
    lowered program."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    return _BUILDERS[combo.engine](combo, devices)


def lint_combo(combo: Combo, devices=None) -> LintReport:
    target, hlo, mesh = lower_combo(combo, devices)
    mesh_model = MeshModel.from_mesh(mesh)
    ctx = LintContext.build(target, hlo, mesh_model)
    return LintReport(
        combo=combo,
        target=target,
        findings=run_rules(ctx),
        n_collectives=len(ctx.collectives),
    )


# ------------------------------------------------------------ matrix


def full_matrix() -> List[Combo]:
    """The engine x mode matrix the acceptance criteria name: every
    engine at S in {2,4,8} on its primary axis, DDP/FSDP/CausalLM-SP in
    all three reduction modes, collective_matmul off/on, hybrid
    2 x (S/2) dcn x ici meshes for the reducer paths, the serving
    decode steps (declarative + opted-in rings), plus the bf16 ring
    combos and the tinycnn (BatchNorm) pre-gate twins."""
    combos: List[Combo] = []
    for s in (2, 4, 8):
        combos += [Combo("cm_ag", s), Combo("cm_rs", s)]
        combos.append(Combo("dp", s))
        for gr in ("monolithic", "bucketed", "overlapped"):
            combos.append(Combo("ddp", s, grad_reduction=gr))
            combos.append(Combo("fsdp", s, grad_reduction=gr))
        combos.append(Combo("tp", s))
        combos.append(Combo("tp", s, collective_matmul=True))
        combos.append(Combo("sp", s))
        combos.append(Combo("sp", s, collective_matmul=True))
    for s in (4, 8):  # hybrid 2 x (S/2) dcn x ici
        for gr in ("bucketed", "overlapped"):
            combos.append(Combo("ddp", s, grad_reduction=gr, dcn=2))
            combos.append(Combo("fsdp", s, grad_reduction=gr, dcn=2))
    for s in (2, 4):  # sp_lm: data axis x seq=2 (2S devices)
        for gr in ("monolithic", "bucketed", "overlapped"):
            combos.append(Combo("sp_lm", s, grad_reduction=gr))
    combos.append(Combo("sp_lm", 4, grad_reduction="bucketed", dcn=2))
    combos.append(Combo("sp_lm", 2, collective_matmul=True))
    for s in (2, 4):  # serving decode step, declarative + opted-in
        combos.append(Combo("serve", s))
        combos.append(Combo("serve", s, collective_matmul=True))
    # Paged serving decode (ISSUE 15): the block-table gathers must
    # not change the decode collective inventory — same serve-decode-
    # ring pin (4L(S-1) tagged permutes, zero monolithic all-gather)
    # on the paged step, declarative and opted-in.
    combos.append(Combo("serve", 2, page_size=8))
    combos.append(Combo("serve", 2, page_size=8,
                        collective_matmul=True))
    combos.append(Combo("serve", 4, page_size=8,
                        collective_matmul=True))
    # Quantized decode compute (ISSUE 16, rule decode-quantized-
    # matmul): int8/bf16 projection GEMMs on the declarative and
    # opted-in-ring decode steps — the ring pin (serve-decode-ring)
    # must stay CLEAN on the same combos, since only the chunk dot
    # arithmetic changes; one paged+ring+int8 combo closes the
    # paging x rings x quantization triangle. (serve/S2/cm/q-int8
    # rides in via pregate_matrix().)
    combos.append(Combo("serve", 2, compute_dtype="int8"))
    combos.append(Combo("serve", 4, collective_matmul=True,
                        compute_dtype="int8"))
    combos.append(Combo("serve", 2, compute_dtype="bf16"))
    combos.append(Combo("serve", 2, collective_matmul=True,
                        compute_dtype="bf16"))
    combos.append(Combo("serve", 2, page_size=8,
                        collective_matmul=True,
                        compute_dtype="int8"))
    # Speculative verify step (ISSUE 18, rule spec-verify-step): the
    # one-pass verify must carry exactly one decode step's ring
    # inventory — pinned at S in {2, 4} and k in {2, 4} on the
    # paged+ringed layout, plus a declarative paged combo (generic
    # rules only) so the k>0 lowering itself stays covered without
    # rings. (serve/S2/pg8/cm/spec2 rides in via pregate_matrix().)
    combos.append(Combo("serve", 2, page_size=8, speculative_k=2))
    combos.append(Combo("serve", 4, page_size=8,
                        collective_matmul=True, speculative_k=2))
    combos.append(Combo("serve", 2, page_size=8,
                        collective_matmul=True, speculative_k=4))
    combos += [Combo("pipeline", 2), Combo("pipeline", 4)]
    # Composed ParallelPlan lowerings (ISSUE 19): the genuinely
    # composed 3-axis plan on all 8 devices plus its fsdp-sharded
    # twin — rules plan-wire-fabric / plan-seq-fabric /
    # plan-grad-fabric pin each axis's collectives to its contracted
    # fabric in the composed lowering. (The 4-device pp2xsp2 plan
    # rides in via pregate_matrix().)
    combos.append(Combo("plan", 8, plan="pp2xsp2xdp2"))
    combos.append(Combo("plan", 8, plan="pp2xsp2xfsdp2"))
    # Scheduled tick programs (ISSUE 20): the 1f1b 3-axis plan, the
    # interleaved V=2 plan over the fsdp per-parameter layout, and the
    # plangate sched cell's gpipe/1f1b twins at M=4 (M just above pp)
    # — plan-wire-fabric pins the per-schedule static ppermute count,
    # and the M4 rows are what bench.py --plan-microbench reconciles
    # its schedule column against.
    combos.append(Combo("plan", 8, plan="pp2-1f1bxsp2xdp2"))
    combos.append(Combo("plan", 8, plan="pp2-int2xfsdp4"))
    combos.append(
        Combo("plan", 8, plan="pp2xdp4", num_microbatches=4)
    )
    combos.append(
        Combo("plan", 8, plan="pp2-1f1bxdp4", num_microbatches=4)
    )
    combos.append(
        Combo("plan", 8, plan="pp2-int2xdp4", num_microbatches=4)
    )
    combos.append(Combo("tp", 4, collective_matmul=True, bf16=True))
    combos.append(Combo("sp", 4, collective_matmul=True, bf16=True))
    # MoE dispatch (PR 10): the GSPMD 'expert'-axis baseline plus the
    # hierarchical exchange at S in {4, 8}, overlapped, and on a
    # 2 x (S/2) hybrid fabric — rule moe-hierarchical-a2a's pins.
    combos.append(Combo("ep", 4))  # gspmd baseline
    combos.append(Combo("ep", 4, moe_dispatch="hierarchical"))
    combos.append(
        Combo("ep", 4, moe_dispatch="hierarchical", moe_overlap=True)
    )
    combos.append(
        Combo("ep", 8, dcn=2, moe_dispatch="hierarchical",
              moe_overlap=True)
    )
    # Quantized 'dcn' wire (PR 11, rule dcn-compressed-payload): the
    # compressed cross-slice hop on every engine that exposes it —
    # reducer modes x {bf16, int8} incl. the monolithic single-bucket
    # path, the CausalLM-SP data buckets, and the hierarchical MoE
    # dispatch (unfused + overlapped).
    combos.append(Combo("ddp", 4, grad_reduction="bucketed", dcn=2,
                        dcn_compression="bf16"))
    combos.append(Combo("ddp", 8, grad_reduction="overlapped", dcn=2,
                        dcn_compression="int8"))
    combos.append(Combo("ddp", 4, grad_reduction="monolithic", dcn=2,
                        dcn_compression="int8"))
    combos.append(Combo("fsdp", 4, grad_reduction="bucketed", dcn=2,
                        dcn_compression="bf16"))
    combos.append(Combo("fsdp", 8, grad_reduction="overlapped", dcn=2,
                        dcn_compression="int8"))
    combos.append(Combo("fsdp", 8, grad_reduction="monolithic", dcn=2,
                        dcn_compression="int8"))
    combos.append(Combo("sp_lm", 4, grad_reduction="bucketed", dcn=2,
                        dcn_compression="bf16"))
    combos.append(Combo("sp_lm", 4, grad_reduction="overlapped",
                        dcn=2, dcn_compression="int8"))
    combos.append(Combo("ep", 4, dcn=2, moe_dispatch="hierarchical",
                        dcn_compression="bf16"))
    combos.append(
        Combo("ep", 8, dcn=2, moe_dispatch="hierarchical",
              moe_overlap=True, dcn_compression="int8")
    )
    combos += pregate_matrix()
    return combos


def pregate_matrix() -> List[Combo]:
    """The tier-1 pre-gate subset (tools/tier1.sh): tinycnn DDP + FSDP
    overlapped — the deepest rule stack (rings + overlap deps + BN
    allowlist + at-rest) — plus one tinycnn-sized hierarchical MoE
    combo on a hybrid fabric, so a dispatch regression fails in seconds
    with `moe-hierarchical-a2a` named, one tinycnn-sized quantized
    hybrid combo so a broken wire codec fails with
    `dcn-compressed-payload` named, one quantized ringed serve
    combo so a broken quantized decode path fails with
    `decode-quantized-matmul` (or a broken ring with
    `serve-decode-ring`) named, and one speculative paged+ringed serve
    combo so a verify step that falls off the rings fails with
    `spec-verify-step` named, and one tiny-GPT-sized composed-plan
    combo (ISSUE 19) so a composed lowering whose collectives leave
    their contracted fabric fails with a plan-* rule named."""
    return [
        Combo("ddp", 8, grad_reduction="overlapped", model="tinycnn"),
        Combo("fsdp", 8, grad_reduction="overlapped", model="tinycnn"),
        Combo("ep", 4, dcn=2, moe_dispatch="hierarchical",
              moe_overlap=True),
        Combo("ddp", 4, grad_reduction="bucketed", dcn=2,
              dcn_compression="int8", model="tinycnn"),
        Combo("serve", 2, collective_matmul=True,
              compute_dtype="int8"),
        Combo("serve", 2, page_size=8, collective_matmul=True,
              speculative_k=2),
        Combo("plan", 4, plan="pp2xsp2"),
    ]


# ------------------------------------------------------------ report


def format_report(rep: LintReport) -> str:
    lines = [
        f"[hlolint] {rep.combo.name}: {rep.n_collectives} collectives, "
        f"{len(rep.violations)} violation(s)"
        + (f", {len(rep.findings) - len(rep.violations)} exempted"
           if len(rep.findings) != len(rep.violations) else "")
    ]
    for f in rep.findings:
        mark = "EXEMPT" if f.exempted else f.severity.upper()
        lines.append(f"[hlolint]   {mark} {f.rule}: {f.message}"
                     + (f" (exempt: {f.exemption_reason})"
                        if f.exempted else ""))
    return "\n".join(lines)


def run(combos: Sequence[Combo], devices=None,
        emit: Callable[[str], None] = print) -> dict:
    """Lint each combo, streaming one partial-JSON line per finished
    combo; returns (and emits) the final summary object."""
    reports = []
    for combo in combos:
        try:
            rep = lint_combo(combo, devices)
        except Exception as e:  # a combo that fails to lower is a finding
            emit(f"[hlolint] {combo.name}: LOWERING FAILED: {e!r}")
            emit(json.dumps({
                "leg": {"name": combo.name, "error": repr(e)},
                "partial": True,
            }))
            reports.append(None)
            continue
        emit(format_report(rep))
        emit(json.dumps({
            "leg": {
                "name": combo.name,
                "violations": len(rep.violations),
                "exempted": len(rep.findings) - len(rep.violations),
                "collectives": rep.n_collectives,
            },
            "partial": True,
        }))
        reports.append(rep)
    ok = [r for r in reports if r is not None]
    summary = {
        "hlo_lint": {
            "targets": len(combos),
            "lowered": len(ok),
            "rules": len(REGISTRY),
            "violations": sum(len(r.violations) for r in ok),
            # A combo that fails to LOWER is an error too: an engine
            # regression that crashes lowering must fail the gates, not
            # slip past them with zero rule findings.
            "errors": sum(len(r.errors) for r in ok)
            + (len(combos) - len(ok)),
            "exempted": sum(
                len(r.findings) - len(r.violations) for r in ok
            ),
            "failed_targets": sorted(
                {r.combo.name for r in ok if r.errors}
                | {c.name for c, r in zip(combos, reports)
                   if r is None}
            ),
        }
    }
    emit(json.dumps(summary))
    return summary


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="hlolint",
        description=(
            "Static HLO collective-contract linter: lower engine x "
            "mode combos on a virtual CPU mesh and check the rule "
            "registry (INTERNALS.md section 8b)."
        ),
    )
    parser.add_argument(
        "--pregate", action="store_true",
        help="tier-1 pre-gate subset (tinycnn DDP/FSDP overlapped)",
    )
    parser.add_argument(
        "--filter", default=None,
        help="regex over combo names (e.g. 'ddp.*dcn')",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument("--devices", type=int, default=8)
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in REGISTRY.values():
            print(f"{r.id:32s} {r.severity:5s} [{r.source}] "
                  f"{r.contract}")
        return 0

    # Virtual CPU devices BEFORE any backend initializes (this
    # environment preloads a TPU PJRT plugin that dials a relay).
    from distributed_model_parallel_tpu.runtime.platform import force_cpu

    force_cpu(args.devices)

    combos = pregate_matrix() if args.pregate else full_matrix()
    if args.filter:
        import re

        combos = [c for c in combos if re.search(args.filter, c.name)]
    if not combos:
        print("[hlolint] no combos match", file=sys.stderr)
        return 2
    summary = run(combos)
    return 1 if summary["hlo_lint"]["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
