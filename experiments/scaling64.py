"""64-way structural scaling evidence (BASELINE.json north star:
>=90% weak-scaling efficiency at 64 chips).

This host has ONE real chip, so the evidence is structural + modeled:

1. Lower the ResNet-50 DDP train step on a 64-device virtual mesh and
   read the collective structure out of the StableHLO: every gradient
   leaf's all-reduce, with its byte count (static truth about what the
   program asks the network for).
2. Compile (XLA optimization pipeline, 64-way) the same step for a
   small model and assert the all-reduce COMBINER ran: the per-leaf
   reduces collapse into O(1) fused all-reduces — the schedule shape
   that actually rides ICI.
3. Feed the measured single-chip step time (BENCH_r*) and the public
   v5e ICI bandwidth into the standard ring all-reduce cost model to
   predict weak-scaling efficiency at 64 chips.

Writes experiments/scaling64.json; summarized in RESULTS.md §3.

Run: python experiments/scaling64.py   (CPU-only, no TPU dial)
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_model_parallel_tpu.runtime.platform import force_cpu  # noqa: E402

force_cpu(64)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from distributed_model_parallel_tpu.models.resnet import resnet50  # noqa: E402
from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn  # noqa: E402
from distributed_model_parallel_tpu.parallel.data_parallel import (  # noqa: E402
    DDPEngine,
)
from distributed_model_parallel_tpu.runtime.mesh import (  # noqa: E402
    MeshSpec,
    make_mesh,
)
from distributed_model_parallel_tpu.training.optim import SGD  # noqa: E402

N = 64
PER_CHIP_BATCH = 256

# Measured on the one real chip (BENCH_r04 / RESULTS.md §1): ResNet-50
# bs256 bf16, 2489 img/s/chip -> 0.1029 s/step, MFU 0.30.
MEASURED_STEP_S = 256 / 2489.0
# Public TPU v5e interconnect: 2D torus, 4 ICI links/chip at 100 GB/s
# per direction aggregate ~400 GB/s/chip; the ring all-reduce along one
# torus axis sees one link pair. Conservative effective bandwidth:
BW_ICI_EFFECTIVE = 100e9  # bytes/s usable per ring direction


def stablehlo_all_reduce_bytes(text):
    """(op count, total reduced bytes) from StableHLO text. The op's
    operand signature `: (tensor<...>) -> ...` trails the (multi-line)
    reducer region, so scan from each op start to its signature."""
    dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "i32": 4}
    n_ops = 0
    total_bytes = 0
    sig = re.compile(r":\s*\(tensor<([^>]+)>\)")
    for m in re.finditer(r'"?stablehlo\.all_reduce"?', text):
        s = sig.search(text, m.end())
        if not s:
            continue
        n_ops += 1
        dims = s.group(1).split("x")
        nelems = 1
        for d in dims[:-1]:
            if d.isdigit():
                nelems *= int(d)
        total_bytes += nelems * dt_bytes.get(dims[-1], 4)
    return n_ops, total_bytes


def main():
    mesh = make_mesh(MeshSpec(data=N))
    assert mesh.shape["data"] == N

    # ---- 1. ResNet-50 DDP: lower (SPMD trace) and read the asks ------
    eng = DDPEngine(
        resnet50(1000), SGD(momentum=0.9), mesh,
        compute_dtype=jnp.bfloat16, donate=False,
    )
    state_aval = jax.eval_shape(eng.init_state, jax.ShapeDtypeStruct(
        (2,), jnp.uint32))
    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(state_aval.params)
    )
    imgs = jax.ShapeDtypeStruct((N * PER_CHIP_BATCH, 224, 224, 3),
                                jnp.float32)
    lbls = jax.ShapeDtypeStruct((N * PER_CHIP_BATCH,), jnp.int32)
    lowered = eng.train_step.lower(
        state_aval, imgs, lbls, jax.ShapeDtypeStruct((), jnp.float32)
    )
    text = lowered.as_text()
    n_ar, ar_bytes = stablehlo_all_reduce_bytes(text)
    grad_bytes_f32 = n_params * 4
    print(f"ResNet-50 params: {n_params/1e6:.1f} M "
          f"({grad_bytes_f32/1e6:.1f} MB f32 grads)")
    print(f"StableHLO all_reduce ops: {n_ar}, reduced bytes: "
          f"{ar_bytes/1e6:.1f} MB")

    # ---- 2. small-model 64-way COMPILE: combiner evidence + one step -
    small = DDPEngine(tiny_cnn(10), SGD(), mesh, donate=False)
    ts = small.init_state(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).rand(N * 4, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, N * 4).astype(np.int32)
    xs, ys = small.shard_batch(x, y)
    compiled = small.train_step.lower(
        ts, xs, ys, jnp.float32(0.1)
    ).compile()
    opt_hlo = compiled.as_text()
    n_opt_ar = len(re.findall(r"all-reduce(?:-start)?\(", opt_hlo))
    small_leaves = len(jax.tree_util.tree_leaves(ts.params))
    # run ONE real 64-way step (virtual devices) — the program executes.
    # Measured: the optimization pipeline COMBINES the per-leaf reduces
    # (17 grad leaves + BN-state pmeans + metric psums -> 1 fused
    # all-reduce op on this backend) — the DDP Reducer's bucketing,
    # done by the compiler.
    ts2, m = compiled(ts, xs, ys, jnp.float32(0.1))
    loss0 = float(m["loss_sum"]) / float(m["count"])
    print(f"tinycnn 64-way compile: {small_leaves} grad leaves -> "
          f"{n_opt_ar} optimized all-reduce ops (CPU backend); one "
          f"step ran, loss {loss0:.3f}")

    # ---- 3. ring all-reduce bandwidth model --------------------------
    # Ring all-reduce moves 2*(N-1)/N * bytes per chip; XLA overlaps it
    # with the backward pass, so the step-time hit is the NON-overlapped
    # remainder. Bound both ends: zero overlap (worst) and the measured
    # backward-dominant overlap (best ~= max(compute, comm)).
    comm_s = 2 * (N - 1) / N * grad_bytes_f32 / BW_ICI_EFFECTIVE
    eff_no_overlap = MEASURED_STEP_S / (MEASURED_STEP_S + comm_s)
    eff_overlap = MEASURED_STEP_S / max(MEASURED_STEP_S, comm_s)
    print(f"ring all-reduce: {comm_s*1e3:.2f} ms vs step "
          f"{MEASURED_STEP_S*1e3:.1f} ms")
    print(f"predicted weak-scaling efficiency @64: "
          f"{eff_no_overlap:.3f} (no overlap) .. {eff_overlap:.3f} "
          f"(full overlap)")

    out = {
        "n_devices": N,
        "per_chip_batch": PER_CHIP_BATCH,
        "model": "resnet50",
        "params_m": round(n_params / 1e6, 2),
        "grad_bytes_f32": grad_bytes_f32,
        "stablehlo_all_reduce_ops": n_ar,
        "stablehlo_all_reduce_bytes": ar_bytes,
        "tinycnn_grad_leaves": small_leaves,
        "tinycnn_optimized_all_reduce_ops": n_opt_ar,
        "tinycnn_64way_step_loss": loss0,
        "measured_step_s_1chip": round(MEASURED_STEP_S, 5),
        "ici_bw_effective_bytes_per_s": BW_ICI_EFFECTIVE,
        "ring_allreduce_s": round(comm_s, 6),
        "predicted_weak_scaling_eff_64_no_overlap": round(
            eff_no_overlap, 4),
        "predicted_weak_scaling_eff_64_full_overlap": round(
            eff_overlap, 4),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scaling64.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
