"""Static alpha-beta cost engine over classified collectives.

ONE home for the per-fabric alpha/beta constants (previously private to
`experiments/scaling64.py` — Narayanan et al. SC'21 compose exactly
this model across fabrics) plus two layers on top of them:

1. **Closed-form composition helpers** — the ring / two-level /
   all-to-all formulas `experiments/scaling64.py` §3a–§3d derive by
   hand, as functions. scaling64 now imports the constants from here
   and ASSERTS its hand-derived rows against these functions within 1%,
   so the prose model and the checked one can never silently drift.

2. **The HLO walker** (`predict_collectives` / `combo_cost`) — prices
   every collective the lint matrix already classified
   (`analysis/collectives.py`: kind, payload bytes, crossed axes,
   ring-vs-monolithic) with a per-kind alpha-beta formula on the fabric
   it crosses, and sums to a per-combo predicted per-step comm time.
   `tools/costgate` compares those predictions against the committed
   ledger (`experiments/cost_ledger.json`) and fails CI — like a lint
   violation — when a combo's predicted step time worsens beyond
   tolerance or a new combo ships with no ledger row.

Caveats, stated once: the walker prices the program the CPU test
backend compiled. That backend float-normalizes bf16 collectives to
f32, so compiled-HLO payload bytes are the F32 envelope (the wire-dtype
contract lives in hlolint's trace-level rule `dcn-compressed-payload`);
and the prediction is COMM time on the modeled TPU fabrics — there is
no compute term for the lint models. Both are fine for the gate's
purpose: the number is a deterministic function of the lowered program,
so a regression in what the program asks the network for moves it.

No jax at module level (the closed-form layer and the ledger tooling
must import without a backend); the walker's heavy imports are
function-local, the `analysis` imports are jax-free by that package's
own contract.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional, Sequence

from distributed_model_parallel_tpu.analysis.collectives import (
    ClassifiedCollective,
    MeshModel,
)

# ------------------------------------------------- per-fabric constants
#
# The SINGLE source of truth (formerly scaling64.py's private block;
# provenance unchanged):
#
# Public TPU v5e interconnect: 2D torus, 4 ICI links/chip at 100 GB/s
# per direction aggregate ~400 GB/s/chip; a ring along one torus axis
# sees one link pair. Conservative effective bandwidth:
BW_ICI_EFFECTIVE = 100e9  # bytes/s usable per ring direction
# Per-hop launch/latency cost of one collective step (alpha; ~1 us is
# the public order of magnitude for one ICI hop + kernel launch).
ALPHA_HOP_S = 1e-6
# Cross-slice (data-center network) effective bandwidth is an order of
# magnitude below ICI — public multislice numbers put per-chip DCN
# throughput in the tens of GB/s aggregate per slice; conservative:
BW_DCN_EFFECTIVE = 25e9  # bytes/s usable across the slice boundary
# Cross-slice hop latency: DCN is a routed network, not a torus link.
ALPHA_DCN_HOP_S = 10e-6

# Wire itemsize per `dcn_compression` mode (`ops/wire_codec.py`): what
# one element of a compressed cross-slice payload costs on the wire.
WIRE_ITEMSIZE = {"none": 4, "f32": 4, "bf16": 2, "int8": 1}

# Decode-compute roofline constants (ISSUE 16, `ops/quant_matmul.py`).
# Public TPU v5e datasheet order of magnitude: ~819 GB/s HBM per chip
# (conservative effective), 197 TFLOP/s bf16 MXU peak with int8 at 2x
# and f32 at 1/4 of bf16 (the MXU's native half path).
BW_HBM_EFFECTIVE = 800e9  # bytes/s effective weight-streaming bandwidth
MXU_RATE = {  # flop/s (multiply-accumulate = 2 flop) per compute mode
    "f32": 49.0e12,
    "bf16": 197.0e12,
    "int8": 394.0e12,
}
# What one weight element costs on the HBM stream per compute mode
# (int8 streams quantized weights; the f32 scale sidecars are noise).
COMPUTE_ITEMSIZE = {"f32": 4, "bf16": 2, "int8": 1}

# Speculative-decoding workload assumptions (ISSUE 18,
# `serving/speculative.py`). NOT physics: the accept rate is a model
# pairing property (how often the draft's proposals survive the
# target's verify) and the draft-cost ratio an architecture property —
# the serve CLI's default draft runs layers//2 of the target stack at
# the same width, so one draft step streams ~half the projection
# weights of one target decode step. Both live in COMPUTE_CONSTANTS so
# the ledger records and drift-checks the assumptions every committed
# speculative row was priced under.
SPEC_MODEL_ACCEPT = 0.7
DRAFT_COST_RATIO = 0.5

#: Every constant the predictions depend on, by name — recorded in the
#: ledger so `tools/costgate` can refuse to compare predictions made
#: under different physics. CONSTANTS is the comm-fabric set the
#: calibration machinery fits (`observability/calibrate.py`);
#: COMPUTE_CONSTANTS is the decode-compute roofline set (hand-only —
#: the CPU sandbox cannot measure MXU physics, so there is nothing to
#: fit). The ledger records and drift-checks BOTH.
CONSTANTS: Dict[str, float] = {
    "bw_ici_effective_bytes_per_s": BW_ICI_EFFECTIVE,
    "bw_dcn_effective_bytes_per_s": BW_DCN_EFFECTIVE,
    "alpha_hop_s": ALPHA_HOP_S,
    "alpha_dcn_hop_s": ALPHA_DCN_HOP_S,
}

COMPUTE_CONSTANTS: Dict[str, float] = {
    "bw_hbm_effective_bytes_per_s": BW_HBM_EFFECTIVE,
    "mxu_f32_flop_per_s": MXU_RATE["f32"],
    "mxu_bf16_flop_per_s": MXU_RATE["bf16"],
    "mxu_int8_flop_per_s": MXU_RATE["int8"],
    # Speculative workload assumptions (ISSUE 18) ride the compute set:
    # hand-only (the CPU sandbox cannot measure a real draft/target
    # pairing), recorded so a changed assumption forces a full reprice.
    "spec_model_accept_rate": SPEC_MODEL_ACCEPT,
    "spec_draft_cost_ratio": DRAFT_COST_RATIO,
}


@dataclasses.dataclass(frozen=True)
class Fabric:
    """One link class in the alpha-beta model."""

    name: str
    alpha_s: float
    bw_bytes_per_s: float


ICI = Fabric("ici", ALPHA_HOP_S, BW_ICI_EFFECTIVE)
DCN = Fabric("dcn", ALPHA_DCN_HOP_S, BW_DCN_EFFECTIVE)


def load_calibration(path: str) -> Dict[str, float]:
    """Constants out of a `calibrate.py` artifact — the MEASURED
    stand-in for the hand block above. Validates the schema and that
    every hand constant has a fitted twin, so a caller swapping
    physics can never silently run on a partial set."""
    import json

    with open(path) as f:
        data = json.load(f)
    constants = data.get("constants")
    if not isinstance(constants, dict):
        raise ValueError(f"{path}: not a calibration file "
                         "(no 'constants' object)")
    missing = sorted(set(CONSTANTS) - set(constants))
    if missing:
        raise ValueError(
            f"{path}: calibration is missing constants "
            f"{', '.join(missing)} — refit with calibrate.py"
        )
    return {k: float(constants[k]) for k in CONSTANTS}


def fabrics_from_constants(
    constants: Dict[str, float],
) -> "tuple[Fabric, Fabric]":
    """(ICI, DCN) fabrics under explicit constants (e.g. a loaded
    calibration) — what a measured-ledger regeneration would price
    with."""
    return (
        Fabric("ici", constants["alpha_hop_s"],
               constants["bw_ici_effective_bytes_per_s"]),
        Fabric("dcn", constants["alpha_dcn_hop_s"],
               constants["bw_dcn_effective_bytes_per_s"]),
    )


# ------------------------------------------- closed-form compositions
#
# The scaling64 §3 formulas as functions. Arguments are payload bytes
# (or elements for the dtype-scaled MoE wire rows), axis sizes, and the
# bucket/op counts the alpha terms multiply. Each multi-fabric form
# takes an optional `constants` dict (the CONSTANTS key set) so the
# tuner can score candidates under a loaded calibration instead of the
# hand block; None keeps the module constants.


def _resolve_constants(constants: Optional[Dict[str, float]]):
    """(bw_ici, alpha_ici, bw_dcn, alpha_dcn) under explicit constants
    (validated against the CONSTANTS key set) or the hand block."""
    if constants is None:
        return (BW_ICI_EFFECTIVE, ALPHA_HOP_S, BW_DCN_EFFECTIVE,
                ALPHA_DCN_HOP_S)
    missing = sorted(set(CONSTANTS) - set(constants))
    if missing:
        raise ValueError(
            f"constants set is missing {', '.join(missing)} — pass a "
            "full CONSTANTS-shaped dict (cost.load_calibration "
            "validates calibration files into one)"
        )
    return (
        constants["bw_ici_effective_bytes_per_s"],
        constants["alpha_hop_s"],
        constants["bw_dcn_effective_bytes_per_s"],
        constants["alpha_dcn_hop_s"],
    )


def ring_all_reduce_s(nbytes: float, size: int, n_ops: int = 1,
                      bw: float = BW_ICI_EFFECTIVE,
                      alpha: float = ALPHA_HOP_S) -> float:
    """Single-fabric ring all-reduce (§3a): 2(S-1)/S of the payload on
    the wire, 2(S-1) latency hops PER OP — `n_ops` counts the unfused
    lowering's op count (1 = bucketed/fused)."""
    if size <= 1:
        return 0.0
    beta = 2 * (size - 1) / size * nbytes / bw
    return beta + n_ops * 2 * (size - 1) * alpha


def two_level_all_reduce_s(nbytes: float, ici: int, dcn: int,
                           n_buckets: int = 1,
                           wire: str = "none",
                           constants: Optional[Dict[str, float]] = None,
                           ) -> float:
    """Hierarchical bucketed reduction over a dcn x ici fabric (§3b /
    §3b'): ring reduce-scatter + all-gather over 'ici' at the full
    payload, the 1/ici shard across 'dcn' — at the wire itemsize when
    compressed (int8 adds one sidecar hop per payload hop, counted in
    alpha; its 4-byte scale payload is noise and not priced)."""
    bw_ici, a_ici, bw_dcn, a_dcn = _resolve_constants(constants)
    wb = WIRE_ITEMSIZE[wire]
    sidecar_hops = 1 if wire == "int8" else 0
    beta = 2 * (ici - 1) / ici * nbytes / bw_ici
    if dcn > 1:
        beta += (
            2 * (dcn - 1) / dcn * (nbytes / ici) * (wb / 4)
            / bw_dcn
        )
    alpha = n_buckets * (
        2 * (ici - 1) * a_ici
        + (1 + sidecar_hops) * 2 * (dcn - 1) * a_dcn
    )
    return beta + alpha


def plan_bubble_factor(pp: int, schedule: str = "gpipe",
                       virtual_stages: int = 1,
                       num_microbatches: int = 0) -> float:
    """Pipeline-span stretch over ideal per-device compute (Narayanan
    et al. SC'21): (V*M + pp - 1) / (V*M). gpipe and 1F1B share the
    fill-drain span (1F1B buys MEMORY, not ticks — the O(S) stash);
    interleaving (V virtual stages per device) divides the bubble by
    V. M defaults to the engine's own default (pp, or pp*V
    interleaved); pp <= 1 has no bubble."""
    if pp <= 1:
        return 1.0
    v = virtual_stages if schedule == "interleaved" else 1
    m = num_microbatches or pp * v
    return (v * m + pp - 1.0) / (v * m)


def composed_plan_step_s(pp: int, sp: int, dp: int,
                         grad_bytes: float, mb: int, seq_len: int,
                         dim: int, vocab: int, n_layers: int,
                         ici: int, dcn: int,
                         fsdp: bool = False,
                         constants: Optional[Dict[str, float]] = None,
                         schedule: str = "gpipe",
                         virtual_stages: int = 1,
                         num_microbatches: int = 0,
                         compute_s: float = 0.0,
                         ) -> float:
    """Asked-bytes step time of one composed `ParallelPlan` training
    step (ISSUE 19/20, `parallel/plan.py`), the plan family's closed
    form. Three collective legs, each pinned to its fabric by the
    hlolint plan-* rules:

    wire — the stage handoff (`plan_wire` ppermute). gpipe: M + pp - 1
      forward ticks (the backward transpose rides the same count),
      each moving one microbatch activation pair
      mb x (seq_len/sp) x max(dim, vocab) floats to the next stage.
      A scheduled plan (1f1b / interleaved, ISSUE 20) replays its tick
      TABLE: 2*M*V + 2*(pp-1) ticks with an explicit backward wire —
      scheduling trades MORE wire ticks for a smaller compute bubble,
      which is exactly the tradeoff the tuner prices. Stages are laid
      across 'dcn' when the fabric is factored (the plan grid admits
      pp>1 at dcn>1 only when the slice boundary falls between
      stages), else ICI.
    seq — ring-attention KV hops over 'seq' (sp-1 ppermutes of the
      mb x (seq_len/sp) x dim K and V shards per chunk) inside every
      tick's chunk slice (n_layers / (pp*V) layers): ICI always
      (plan-seq-fabric pins it).
    grad — ONE fused gradient psum over ('stage','data','seq')
      (`plan_grad`): multislice XLA decomposes a global all-reduce
      hierarchically, so at dcn>1 it prices as the two-level form over
      (group/dcn) x dcn, else a flat ring over the whole group.
    fsdp adds the per-step param all-gather (`plan_fsdp_gather`) over
      'data' — DCN-facing only when the data axis is what crosses the
      slice boundary (pp == 1).
    compute_s (optional) — the plan's ideal per-device step compute
      (`plan_step_compute_s`), folded in under `plan_bubble_factor`:
      the term the schedule knob actually shrinks. 0 keeps the
      comm-only form (every pre-ISSUE-20 caller prices identically).

    `num_microbatches=0` means the engine default (M = pp, or pp*V
    interleaved) — under which the gpipe wire tick count is the
    historical 2*pp - 1."""
    bw_ici, a_ici, bw_dcn, a_dcn = _resolve_constants(constants)
    v = virtual_stages if schedule == "interleaved" else 1
    m = num_microbatches or pp * v
    scheduled = schedule != "gpipe" and pp > 1
    if scheduled:
        ticks = 2 * m * v + 2 * (pp - 1)
    else:
        ticks = m + pp - 1  # == 2*pp - 1 at the default M = pp
    total = 0.0
    if pp > 1:
        wire_bytes = mb * (seq_len // sp) * max(dim, vocab) * 4
        bw, a = (bw_dcn, a_dcn) if dcn > 1 else (bw_ici, a_ici)
        total += ticks * (a + wire_bytes / bw)
    if sp > 1:
        kv_bytes = 2 * mb * (seq_len // sp) * dim * 4
        total += (
            ticks * (n_layers // (pp * v)) * (sp - 1)
            * (a_ici + kv_bytes / bw_ici)
        )
    group = pp * sp * dp
    if group > 1:
        if dcn > 1:
            total += two_level_all_reduce_s(
                grad_bytes, group // dcn, dcn, n_buckets=1,
                constants=constants,
            )
        else:
            total += ring_all_reduce_s(
                grad_bytes, group, 1, bw_ici, a_ici
            )
    if fsdp and dp > 1:
        bw, a = (
            (bw_dcn, a_dcn) if (dcn > 1 and pp == 1)
            else (bw_ici, a_ici)
        )
        total += (dp - 1) * a + (dp - 1) / dp * grad_bytes / bw
    if compute_s:
        total += compute_s * plan_bubble_factor(
            pp, schedule, virtual_stages, num_microbatches
        )
    return total


def plan_step_compute_s(n_params: float, tokens: float, shards: int,
                        mode: str = "f32",
                        constants: Optional[
                            Dict[str, float]] = None) -> float:
    """Ideal per-device arithmetic of one dense train step: the
    standard 6 flop per parameter per token (2 forward + 4 backward),
    split over the plan's pp*sp*dp shards, at the MXU rate — training
    GEMMs are large, so unlike decode (`quant_matmul_s`) the weight
    stream amortizes and the MXU bound is the one that binds."""
    if mode not in MXU_RATE:
        raise ValueError(
            f"mode must be one of {sorted(MXU_RATE)}, got {mode!r}"
        )
    c = _resolve_compute_constants(constants)
    return (
        6.0 * n_params * tokens / shards
        / c[f"mxu_{mode}_flop_per_s"]
    )


def flat_all_to_all_s(elems: int, itemsize: int, ici: int,
                      dcn: int,
                      constants: Optional[Dict[str, float]] = None,
                      ) -> float:
    """One flat (partitioner-shaped) token exchange over the joint
    dcn x ici fabric (§3c): (K-1)/K of the payload crosses the slice
    boundary in (K-1)*I fragments; the intra-slice share rides ICI."""
    bw_ici, a_ici, bw_dcn, a_dcn = _resolve_constants(constants)
    x_bytes = elems * itemsize
    n = ici * dcn
    return (
        (dcn - 1) / dcn * x_bytes / bw_dcn
        + (ici - 1) / n * x_bytes / bw_ici
        + (dcn - 1) * ici * a_dcn
        + (ici - 1) * a_ici
    )


def hierarchical_all_to_all_s(elems: int, itemsize: int, ici: int,
                              dcn: int,
                              wire: Optional[str] = None,
                              constants: Optional[
                                  Dict[str, float]] = None) -> float:
    """One two-level token exchange (§3c / §3c',
    `ops/expert_dispatch.py`): same cross-slice bytes as the flat form
    but in K-1 contiguous messages of the 1/ici-regrouped shard — at
    the wire itemsize when compressed — and the intra-slice share on
    ICI exclusively."""
    bw_ici, a_ici, bw_dcn, a_dcn = _resolve_constants(constants)
    x_bytes = elems * itemsize
    dcn_itemsize = itemsize if wire in (None, "none") \
        else WIRE_ITEMSIZE[wire]
    return (
        (dcn - 1) / dcn * (elems * dcn_itemsize) / bw_dcn
        + (ici - 1) / ici * x_bytes / bw_ici
        + (dcn - 1) * a_dcn
        + (ici - 1) * a_ici
    )


def serve_paged_request_s(live_tokens: int, prompt_tokens: int,
                          new_tokens: int, token_bytes: int,
                          page_size: int, prefill_chunk: int,
                          constants: Optional[Dict[str, float]] = None,
                          ) -> float:
    """Per-request serving cost of one paged-cache configuration
    (ISSUE 15 / ROADMAP 5c — the serve tuning family's closed form).

    Two knob-driven tradeoffs, both alpha-beta shaped and both
    EXHIBITED by the compiled/host path (the gather side of the decode
    step reads the full block-table width whatever the page size, so
    it is knob-neutral and deliberately NOT modeled):

    * **page_size** — each decode step scatters back ONE whole page
      per slot (`_scatter_written_page`): page_size * token_bytes of
      write traffic per generated token (beta — larger pages rewrite
      more unchanged positions), against ceil(total/p) page
      allocations per sequence lifetime (alpha — smaller pages
      allocate, and grow the block table, more often).
    * **prefill_chunk** — ingestion runs ceil(prompt/c) chunk launches
      (alpha) over ceil(prompt/c)*c padded token positions of compute
      traffic (beta — the padding waste a smaller chunk trims), per
      admitted request.

    `live_tokens` (the batch's concurrent token load) is accepted for
    payload-shape stability but deliberately UNPRICED: the per-request
    form charges only this request's own pages, and the batch-level
    gather is knob-neutral (above). Priced with the ICI constant pair
    as the on-chip (HBM) proxy — the same CPU-physics honesty note as
    every other closed form here: on this sandbox the constants rank
    configurations, they do not predict wall clock on real silicon.

    Both knobs must be >= 1: this form prices PAGED, CHUNKED
    configurations only (0 is the CLI/Combo sentinel for
    contiguous/monolithic, which has no page or chunk tradeoff to
    price)."""
    if page_size < 1 or prefill_chunk < 1:
        raise ValueError(
            "serve_paged_request_s prices paged+chunked serving: "
            f"page_size ({page_size}) and prefill_chunk "
            f"({prefill_chunk}) must be >= 1 (0 is the "
            "contiguous/monolithic sentinel, which this form cannot "
            "price)"
        )
    del live_tokens  # unpriced (docstring)
    bw_ici, a_ici, _, _ = _resolve_constants(constants)
    # Decode: one page of write-back per generated token (the written
    # page rewrites in full), plus one allocation launch each time
    # THIS sequence crosses into a new page over its lifetime.
    total_tokens = prompt_tokens + new_tokens
    decode_writes = new_tokens * (
        a_ici + page_size * token_bytes / bw_ici
    )
    allocations = -(-total_tokens // page_size) * a_ici
    chunks = -(-prompt_tokens // prefill_chunk)
    prefill = chunks * a_ici \
        + chunks * prefill_chunk * token_bytes / bw_ici
    return prefill + decode_writes + allocations


def _resolve_compute_constants(
    constants: Optional[Dict[str, float]],
) -> Dict[str, float]:
    """A full COMPUTE_CONSTANTS-shaped dict, validated, or the hand
    block — the compute twin of `_resolve_constants` (the comm set and
    the compute set are separate dicts because only the comm constants
    are calibratable on this sandbox)."""
    if constants is None:
        return COMPUTE_CONSTANTS
    missing = sorted(set(COMPUTE_CONSTANTS) - set(constants))
    if missing:
        raise ValueError(
            f"compute constants set is missing {', '.join(missing)} — "
            "pass a full COMPUTE_CONSTANTS-shaped dict"
        )
    return constants


def quant_matmul_s(m: int, k: int, n: int, mode: str = "f32",
                   constants: Optional[Dict[str, float]] = None,
                   ) -> float:
    """Roofline time of ONE decode projection GEMM x (k, n) in `mode`
    arithmetic (`ops/quant_matmul.py`): max(weight-streaming HBM time,
    MXU flop time). Decode's m is the slot batch — tiny — so the
    k*n*itemsize weight stream dominates, which is exactly the term
    quantization divides (int8 streams 1/4 the bytes of f32 AND runs
    the MXU at 8x its f32 rate; the roofline picks whichever bound
    still binds)."""
    if mode not in MXU_RATE:
        raise ValueError(
            f"mode must be one of {sorted(MXU_RATE)}, got {mode!r}"
        )
    c = _resolve_compute_constants(constants)
    hbm_s = k * n * COMPUTE_ITEMSIZE[mode] \
        / c["bw_hbm_effective_bytes_per_s"]
    mxu_s = 2.0 * m * k * n / c[f"mxu_{mode}_flop_per_s"]
    return max(hbm_s, mxu_s)


def serve_decode_compute_s(layers: int, dim: int, ffn_dim: int,
                           n_slots: int, mode: str = "f32",
                           shards: int = 1,
                           constants: Optional[
                               Dict[str, float]] = None) -> float:
    """Per-decode-step projection-GEMM compute of the serving engine
    (ISSUE 16): the 4 opted-in projections per block — qkv (dim ->
    3*dim), attn-out (dim -> dim), ffn-in (dim -> ffn), ffn-out (ffn ->
    dim) — times `layers`, each 1/shards per device under the tp
    layout (Megatron column/row splits shard one weight dimension; the
    ring and declarative lowerings stream the same per-device bytes).
    The head matmul and attention dots deliberately stay f32 and are
    mode-neutral, so they are not priced — this form exists to rank
    compute modes, the same honesty note as every closed form here."""
    projections = (
        (dim, 3 * dim),      # fused qkv
        (dim, dim),          # attention out
        (dim, ffn_dim),      # ffn in
        (ffn_dim, dim),      # ffn out
    )
    per_block = sum(
        quant_matmul_s(n_slots, k, -(-n // shards), mode, constants)
        for k, n in projections
    )
    return layers * per_block


def serve_combo_compute_s(combo,
                          constants: Optional[
                              Dict[str, float]] = None) -> float:
    """The decode-compute roofline of ONE lint-matrix serve combo.
    Model facts mirror `lint._build_serve`'s proxy (GPT dim 16 / ffn 32
    / 2 layers, 2*S slots over S 'model' shards) — shared by
    `combo_cost` and the tuner's lowering tier
    (`tuning/search.search_cell`) so the committed ledger and the
    committed plans price the same form."""
    return serve_decode_compute_s(
        layers=2, dim=16, ffn_dim=32, n_slots=2 * combo.size,
        mode=combo.compute_dtype or "f32", shards=combo.size,
        constants=constants,
    )


# ------------------------------------- speculative decoding (ISSUE 18)


def speculative_expected_tokens(accept_rate: float, k: int) -> float:
    """Expected ACCEPTED tokens per speculative round (Leviathan et
    al., ICML'23 eq. 1): position i of the k drafts lands iff all of
    its predecessors did, and the round always emits one bonus token —
    sum_{i=0..k} acc^i = (1 - acc^(k+1)) / (1 - acc). k=0 degenerates
    to 1.0 (plain decode); acc=1 to k+1 (every draft survives)."""
    if k <= 0:
        return 1.0
    if not 0.0 <= accept_rate <= 1.0:
        raise ValueError(
            f"accept_rate must be in [0, 1], got {accept_rate!r}"
        )
    if accept_rate >= 1.0:
        return float(k + 1)
    return (1.0 - accept_rate ** (k + 1)) / (1.0 - accept_rate)


def serve_verify_compute_s(layers: int, dim: int, ffn_dim: int,
                           n_slots: int, speculative_k: int,
                           mode: str = "f32", shards: int = 1,
                           constants: Optional[
                               Dict[str, float]] = None) -> float:
    """Projection-GEMM roofline of ONE speculative verify step: the
    decode form with m = n_slots * (k+1) — the target scores all k
    draft positions plus the bonus in a single chunk-shaped pass. At
    decode batch sizes the k*n weight STREAM binds the roofline and is
    independent of m, so the verify step prices (almost) identically
    to one plain decode step — which is exactly the win the per-token
    form below amortizes over the expected accepted tokens."""
    return serve_decode_compute_s(
        layers, dim, ffn_dim, n_slots * (speculative_k + 1), mode,
        shards, constants,
    )


def serve_speculative_token_s(decode_step_s: float,
                              verify_step_s: float, speculative_k: int,
                              accept_rate: Optional[float] = None,
                              draft_cost_ratio: Optional[float] = None,
                              constants: Optional[
                                  Dict[str, float]] = None) -> float:
    """Advisory per-ACCEPTED-token cost of the speculative serving
    path: one round = k draft decode steps (each DRAFT_COST_RATIO of a
    plain target step) + ONE verify step, amortized over the round's
    expected accepted tokens. Defaults come from COMPUTE_CONSTANTS so
    the ledger drift-checks the assumptions; explicit overrides let
    `bench.py` put a MEASURED accept rate next to the model's."""
    if speculative_k < 1:
        raise ValueError(
            "serve_speculative_token_s prices k >= 1 rounds (a plain "
            "decode step IS the k=0 per-token cost)"
        )
    c = _resolve_compute_constants(constants)
    acc = c["spec_model_accept_rate"] if accept_rate is None \
        else accept_rate
    ratio = c["spec_draft_cost_ratio"] if draft_cost_ratio is None \
        else draft_cost_ratio
    e = speculative_expected_tokens(acc, speculative_k)
    return (speculative_k * ratio * decode_step_s + verify_step_s) / e


def serve_speculative_request_s(prompt_tokens: int, new_tokens: int,
                                token_bytes: int, page_size: int,
                                prefill_chunk: int, speculative_k: int,
                                decode_compute_s: float = 0.0,
                                verify_compute_s: float = 0.0,
                                constants: Optional[
                                    Dict[str, float]] = None,
                                compute_constants: Optional[
                                    Dict[str, float]] = None) -> float:
    """Per-request closed form of SPECULATIVE paged serving (the serve
    tuning family's k >= 1 form; `tuning/search.serve_closed_form_s`
    dispatches here). Prefill and page-allocation terms follow
    `serve_paged_request_s` — the draft ingests every prompt itself
    (prefix cache is target-side only), charged at DRAFT_COST_RATIO of
    the target's prefill — and the per-token decode loop is replaced
    by new_tokens / E speculative rounds priced by
    `serve_speculative_token_s`, each step one page of write-back plus
    its compute term. Same CPU-physics honesty note as every closed
    form here: the constants rank configurations."""
    if page_size < 1 or prefill_chunk < 1:
        raise ValueError(
            "serve_speculative_request_s prices paged+chunked serving: "
            f"page_size ({page_size}) and prefill_chunk "
            f"({prefill_chunk}) must be >= 1"
        )
    if speculative_k < 1:
        raise ValueError(
            "serve_speculative_request_s prices k >= 1 "
            "(serve_paged_request_s is the k=0 form)"
        )
    bw_ici, a_ici, _, _ = _resolve_constants(constants)
    cc = _resolve_compute_constants(compute_constants)
    total_tokens = prompt_tokens + new_tokens
    chunks = -(-prompt_tokens // prefill_chunk)
    prefill = chunks * a_ici \
        + chunks * prefill_chunk * token_bytes / bw_ici
    allocations = -(-total_tokens // page_size) * a_ici
    step_comm = a_ici + page_size * token_bytes / bw_ici
    token_s = serve_speculative_token_s(
        step_comm + decode_compute_s, step_comm + verify_compute_s,
        speculative_k, constants=compute_constants,
    )
    return (
        (1.0 + cc["spec_draft_cost_ratio"]) * prefill
        + allocations
        + new_tokens * token_s
    )


# ------------------------------------------------------ the HLO walker


@dataclasses.dataclass
class CostBreakdown:
    """Per-combo prediction: alpha/beta split and per-fabric totals.
    `total_s` is the predicted per-step comm time — the ledger's gated
    number."""

    alpha_s: float = 0.0
    beta_s: float = 0.0
    n_collectives: int = 0
    bytes_by_fabric: Dict[str, int] = dataclasses.field(
        default_factory=dict
    )
    seconds_by_fabric: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )

    @property
    def total_s(self) -> float:
        return self.alpha_s + self.beta_s

    def as_row(self) -> dict:
        """The ledger row (stable rounding so regenerated ledgers diff
        cleanly)."""
        return {
            "predicted_step_s": round(self.total_s, 9),
            "alpha_s": round(self.alpha_s, 9),
            "beta_s": round(self.beta_s, 9),
            "n_collectives": self.n_collectives,
            "bytes_by_fabric": {
                k: int(v) for k, v in sorted(
                    self.bytes_by_fabric.items()
                )
            },
            "seconds_by_fabric": {
                k: round(v, 9) for k, v in sorted(
                    self.seconds_by_fabric.items()
                )
            },
        }


def _collective_cost(kind: str, nbytes: int, group: int,
                     fabric: Fabric) -> tuple:
    """(alpha_s, beta_s) of ONE collective instruction under the ring
    model on its fabric. A collective-permute is one ring hop (the
    chunked decompositions appear as S-1 separate instructions, which
    sums back to the ring totals); the monolithic fused forms get the
    standard ring decomposition costs."""
    if kind == "collective-permute":
        return fabric.alpha_s, nbytes / fabric.bw_bytes_per_s
    if group <= 1:
        return 0.0, 0.0
    if kind == "all-reduce":
        return (
            2 * (group - 1) * fabric.alpha_s,
            2 * (group - 1) / group * nbytes / fabric.bw_bytes_per_s,
        )
    # all-gather / reduce-scatter / all-to-all: one payload traversal.
    return (
        (group - 1) * fabric.alpha_s,
        (group - 1) / group * nbytes / fabric.bw_bytes_per_s,
    )


def predict_collectives(
    collectives: Sequence[ClassifiedCollective],
    mesh: MeshModel,
    dcn_axis: Optional[str] = None,
    fabrics: Optional["tuple[Fabric, Fabric]"] = None,
) -> CostBreakdown:
    """Price every classified collective and sum. Fabric assignment is
    the mesh's: a collective whose membership crosses `dcn_axis` is
    priced on DCN (the slow fabric gates it); everything else rides
    ICI. Unclassifiable membership (axes=None) is conservatively priced
    as crossing every non-trivial axis — the same worst-case answer the
    lint rules give it. `fabrics` = an explicit (ici, dcn) pair (e.g.
    `fabrics_from_constants(load_calibration(...))` — the tuner's
    measured-physics path); None keeps the hand constants."""
    ici_fabric, dcn_fabric = fabrics if fabrics is not None \
        else (ICI, DCN)
    nontrivial = frozenset(
        a for a, s in zip(mesh.axis_names, mesh.shape) if s > 1
    )
    out = CostBreakdown()
    for c in collectives:
        axes = c.axes if c.axes is not None else nontrivial
        if not axes:
            continue  # single-device membership: free
        fabric = dcn_fabric \
            if (dcn_axis is not None and dcn_axis in axes) \
            else ici_fabric
        group = 1
        for a in axes:
            group *= mesh.size(a)
        alpha, beta = _collective_cost(
            c.kind, c.payload_bytes, group, fabric
        )
        out.alpha_s += alpha
        out.beta_s += beta
        out.n_collectives += 1
        out.bytes_by_fabric[fabric.name] = (
            out.bytes_by_fabric.get(fabric.name, 0) + c.payload_bytes
        )
        out.seconds_by_fabric[fabric.name] = (
            out.seconds_by_fabric.get(fabric.name, 0.0) + alpha + beta
        )
    return out


def combo_cost(combo, devices=None, constants=None) -> dict:
    """Lower ONE lint-matrix combo (reusing the lint driver's builders
    — the same model, mesh, and compiled HLO the rules judge) and
    return its ledger row. Heavy: compiles on the virtual mesh.
    `constants` (a CONSTANTS-shaped dict, e.g. a loaded calibration)
    swaps the pricing physics; the lowering is unchanged."""
    from distributed_model_parallel_tpu.analysis.hlo import parse_hlo
    from distributed_model_parallel_tpu.analysis.collectives import (
        classify,
    )
    from distributed_model_parallel_tpu.analysis.lint import lower_combo

    target, hlo, mesh = lower_combo(combo, devices)
    mesh_model = MeshModel.from_mesh(mesh)
    collectives = classify(parse_hlo(hlo), mesh_model)
    breakdown = predict_collectives(
        collectives, mesh_model, target.dcn_axis,
        fabrics=fabrics_from_constants(constants)
        if constants is not None else None,
    )
    row = breakdown.as_row()
    if combo.engine == "serve":
        row = add_serve_compute(row, combo)
    elif combo.engine == "plan":
        row = add_plan_compute(row, combo, constants)
    return row


def plan_combo_compute_s(combo,
                         constants: Optional[
                             Dict[str, float]] = None) -> float:
    """The ideal (bubble-free) per-device compute of ONE lint-matrix
    plan combo. Model facts mirror `lint._build_plan`'s proxy — the
    `_gpt_cfg` GPT with its stack widened to a pp*V multiple, fed ids
    of shape (4 * dp * pp, 16) — shared by `combo_cost` and the
    tuner's lowering tier so the committed ledger and the committed
    plans price the same form. Heavy (jax.eval_shape) but compile-free;
    both callers have already lowered the combo."""
    import math

    import jax
    import jax.numpy as jnp

    from distributed_model_parallel_tpu.analysis.lint import _gpt_cfg
    from distributed_model_parallel_tpu.models.gpt import gpt_lm
    from distributed_model_parallel_tpu.tuning.space import (
        plan_spec_axes,
    )

    ax = plan_spec_axes(combo.plan)
    chunks = ax["pp"] * ax["virtual"]
    cfg = _gpt_cfg()
    if cfg.num_layers % chunks:
        cfg = dataclasses.replace(cfg, num_layers=chunks)
    key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
    p_aval, _ = jax.eval_shape(gpt_lm(cfg).init, key_aval)
    n_params = sum(
        int(math.prod(leaf.shape) or 1)
        for leaf in jax.tree_util.tree_leaves(p_aval)
    )
    tokens = 4 * ax["dp"] * ax["pp"] * cfg.max_position
    shards = ax["pp"] * ax["sp"] * ax["dp"]
    return plan_step_compute_s(
        n_params, tokens, shards, constants=constants
    )


def add_plan_compute(row: dict, combo,
                     constants: Optional[
                         Dict[str, float]] = None) -> dict:
    """Fold the train-compute roofline into one plan ledger row
    (ISSUE 20) — gpipe combos too, so the cross-schedule deltas are
    visible in the committed ledger. The lowered comm breakdown in
    `row` prices each STATIC collective once, which is identical
    across schedules (the scheduled program has the same gather /
    wire / fused-psum inventory as its gpipe twin by construction);
    the bubble-stretched compute term is what the schedule knob
    actually moves, so it is the differentiator `predicted_step_s`
    carries into the tuner's argmin."""
    from distributed_model_parallel_tpu.tuning.space import (
        plan_spec_axes,
    )

    compute_s = plan_combo_compute_s(combo, constants)
    ax = plan_spec_axes(combo.plan)
    bubble = plan_bubble_factor(
        ax["pp"], ax["schedule"], ax["virtual"],
        getattr(combo, "num_microbatches", 0),
    )
    row = dict(row)
    row["train_compute_s"] = round(compute_s, 12)
    row["bubble_factor"] = round(bubble, 9)
    row["predicted_step_s"] = round(
        row["predicted_step_s"] + compute_s * bubble, 9
    )
    return row


def add_serve_compute(row: dict, combo,
                      constants: Optional[
                          Dict[str, float]] = None) -> dict:
    """Fold the decode-compute roofline into one serve ledger row —
    f32 combos too, so the cross-dtype deltas are visible in the
    committed ledger (`decode_compute_s` carries the mode's own term;
    `predicted_step_s` stays the single gated number).

    Speculative combos (ISSUE 18): the lowered HLO for a
    `speculative_k > 0` serve combo IS the verify step, so the comm
    breakdown already in `row` is the verify step's. The gated number
    becomes the per-ACCEPTED-token cost of one speculative round
    (`serve_speculative_token_s` over comm+compute steps) — directly
    comparable to a plain combo's per-step (= per-token) number, which
    is what lets the tuner's lowering tier rank k > 0 candidates
    against k = 0 on the same axis."""
    compute_s = serve_combo_compute_s(combo, constants)
    row = dict(row)
    row["compute_dtype"] = combo.compute_dtype or "f32"
    row["decode_compute_s"] = round(compute_s, 12)
    k = getattr(combo, "speculative_k", 0)
    if not k:
        row["predicted_step_s"] = round(
            row["predicted_step_s"] + compute_s, 9
        )
        return row
    comm_s = row["predicted_step_s"]  # the verify step's lowered comm
    verify_s = serve_verify_compute_s(
        layers=2, dim=16, ffn_dim=32, n_slots=2 * combo.size,
        speculative_k=k, mode=combo.compute_dtype or "f32",
        shards=combo.size, constants=constants,
    )
    cc = _resolve_compute_constants(constants)
    row["verify_compute_s"] = round(verify_s, 12)
    row["speculative"] = {
        "k": k,
        "accept_rate": cc["spec_model_accept_rate"],
        "draft_cost_ratio": cc["spec_draft_cost_ratio"],
        "expected_tokens_per_round": round(
            speculative_expected_tokens(
                cc["spec_model_accept_rate"], k
            ), 6
        ),
        "verify_step_s": round(comm_s + verify_s, 9),
    }
    row["predicted_step_s"] = round(
        serve_speculative_token_s(
            comm_s + compute_s, comm_s + verify_s, k,
            constants=constants,
        ), 9
    )
    return row


__all__ = [
    "ALPHA_DCN_HOP_S",
    "ALPHA_HOP_S",
    "BW_DCN_EFFECTIVE",
    "BW_HBM_EFFECTIVE",
    "BW_ICI_EFFECTIVE",
    "COMPUTE_CONSTANTS",
    "COMPUTE_ITEMSIZE",
    "CONSTANTS",
    "CostBreakdown",
    "DCN",
    "DRAFT_COST_RATIO",
    "Fabric",
    "ICI",
    "MXU_RATE",
    "SPEC_MODEL_ACCEPT",
    "WIRE_ITEMSIZE",
    "add_plan_compute",
    "add_serve_compute",
    "combo_cost",
    "composed_plan_step_s",
    "plan_bubble_factor",
    "plan_combo_compute_s",
    "plan_step_compute_s",
    "serve_combo_compute_s",
    "fabrics_from_constants",
    "flat_all_to_all_s",
    "hierarchical_all_to_all_s",
    "quant_matmul_s",
    "serve_decode_compute_s",
    "serve_paged_request_s",
    "serve_speculative_request_s",
    "serve_speculative_token_s",
    "serve_verify_compute_s",
    "speculative_expected_tokens",
    "load_calibration",
    "predict_collectives",
    "ring_all_reduce_s",
    "two_level_all_reduce_s",
]
