"""Compute ops: attention cores (reference-free — the reference has no
attention model; BERT-base is demanded by BASELINE.json's configs), and
Pallas TPU kernels for the hot paths."""
