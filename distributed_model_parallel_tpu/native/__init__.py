"""native/ — C++ runtime components for the input-pipeline hot loop.

The reference's input path rides PyTorch's native layer (torchvision C
image ops + the DataLoader C++ worker pool); this package is the
TPU-framework equivalent: `augment.cpp` implements the batched
RandomCrop+RandomHorizontalFlip+normalize transform with an internal
std::thread pool, compiled on first use with the image's g++ (no pip
deps; ctypes binding, no pybind11) and cached next to the source.

Everything degrades gracefully: if the toolchain or the compiled
library is unavailable, `lib()` returns None and the Loader falls back
to the vectorized NumPy implementation with identical numerics
(tests/test_native.py asserts bit-exact parity between the two).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "augment.cpp")
_SO = os.path.join(_DIR, "libdmp_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-pthread",
        "-o", _SO, _SRC,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
        return proc.returncode == 0 and os.path.exists(_SO)
    except (OSError, subprocess.TimeoutExpired):
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, compiling it on first call; None when
    the native path is unavailable (missing toolchain, failed build)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if stale and not _compile():
            return None
        try:
            cdll = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        ci = ctypes.c_int
        cdll.dmp_augment_normalize.argtypes = [
            u8p, ci, ci, ci, ci, i32p, i32p, u8p, ci, f32p, f32p, f32p, ci
        ]
        cdll.dmp_augment_normalize.restype = None
        cdll.dmp_normalize.argtypes = [u8p, ci, ci, ci, ci, f32p, f32p,
                                       f32p, ci]
        cdll.dmp_normalize.restype = None
        _lib = cdll
        return _lib


def available() -> bool:
    return lib() is not None


def augment_normalize(
    images: np.ndarray,
    ys: np.ndarray,
    xs: np.ndarray,
    flips: np.ndarray,
    padding: int,
    mean: np.ndarray,
    std: np.ndarray,
    workers: int = 1,
) -> np.ndarray:
    """Batched crop+flip+normalize on uint8 NHWC via the native library.
    Caller guarantees `lib()` is not None. The ctypes call releases the
    GIL, so prefetch threads overlap this with the device step."""
    cdll = lib()
    n, h, w, c = images.shape
    out = np.empty((n, h, w, c), np.float32)
    cdll.dmp_augment_normalize(
        np.ascontiguousarray(images), n, h, w, c,
        ys.astype(np.int32), xs.astype(np.int32),
        flips.astype(np.uint8), padding,
        mean.astype(np.float32), std.astype(np.float32), out, workers,
    )
    return out


def normalize(
    images: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    workers: int = 1,
) -> np.ndarray:
    cdll = lib()
    n, h, w, c = images.shape
    out = np.empty((n, h, w, c), np.float32)
    cdll.dmp_normalize(
        np.ascontiguousarray(images), n, h, w, c,
        mean.astype(np.float32), std.astype(np.float32), out, workers,
    )
    return out
