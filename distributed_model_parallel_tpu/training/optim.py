"""Optimizer + LR schedule, matching the reference trainer semantics.

Reference optimizer surface (`code/distributed_training/data_parallel.py:90-96`):
  SGD(lr, momentum=0.9, weight_decay=1e-4)
  CosineAnnealingLR(T_max=90) stepped once per epoch via the
  `scheduler.step(last_epoch+1)` idiom (`data_parallel.py:163`)
  pytorch_warmup.LinearWarmup(warmup_period=10) dampening
  (`data_parallel.py:96,164`)

The pipeline launcher uses the same optimizer per stage with flag-settable
momentum/wd (`model_parallel.py:105-108,131-133,146-149`).

Implemented as pure functions over param pytrees so every engine (DP jit,
DDP shard_map, pipeline stages) shares one optimizer; momentum buffers are
an explicit pytree the engines shard alongside params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class SGDState(NamedTuple):
    momentum: Any  # pytree like params


@dataclasses.dataclass(frozen=True)
class SGD:
    """torch-semantics SGD: grad += wd*param; buf = m*buf + grad;
    param -= lr*buf. Weight decay is applied to every param (the reference
    decays BN scale/bias too — `optim.SGD(net.parameters(), ...)`)."""

    momentum: float = 0.9
    weight_decay: float = 1e-4

    def init(self, params) -> SGDState:
        return SGDState(jax.tree_util.tree_map(jnp.zeros_like, params))

    def update(self, params, opt_state: SGDState, grads, lr):
        m, wd = self.momentum, self.weight_decay
        # Two passes, no per-leaf tuples: a (p, buf) tuple-leaf scheme breaks
        # when the params pytree root is itself a tuple (pipeline engines
        # carry params as a per-stage tuple).
        new_buf = jax.tree_util.tree_map(
            lambda p, buf, g: m * buf + g + wd * p,
            params, opt_state.momentum, grads,
        )
        new_params = jax.tree_util.tree_map(
            lambda p, buf: p - lr * buf, params, new_buf
        )
        return new_params, SGDState(new_buf)


def cosine_warmup_schedule(
    base_lr: float, t_max: int = 90, warmup_period: int = 10
) -> Callable[[jax.Array], jax.Array]:
    """Per-epoch LR: cosine(T_max=90) × linear-warmup dampening(10).

    Faithful to the reference composition: `CosineAnnealingLR` closed form
    lr = base·(1+cos(π·epoch/T_max))/2, multiplied by pytorch_warmup's
    dampening factor min(1, (epoch+1)/warmup_period). Epochs past T_max
    follow the cosine back up, exactly as torch's closed-form does when
    driven by `step(last_epoch+1)` for 100 epochs (`data_parallel.py:160-163`).
    """

    def lr(epoch):
        epoch = jnp.asarray(epoch, jnp.float32)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * epoch / t_max))
        warm = jnp.minimum(1.0, (epoch + 1.0) / warmup_period)
        return base_lr * cos * warm

    return lr
