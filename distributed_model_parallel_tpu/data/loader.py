"""Per-host sharded input pipeline with prefetch and a native hot loop.

Fixes the two input-path defects SURVEY.md calls out:
* the reference has **no DistributedSampler** — every rank shuffles the
  whole dataset independently (`utils.py:21` `train_sampler=None`); here
  each host deterministically owns a disjoint shard per epoch.
* the reference funnels all data through device 0 (`Readme.md:15`); here
  each host feeds only its local shard, and the engine's `shard_batch`
  places it along the 'data' mesh axis.

Augmentations are the reference's CIFAR train transforms
(`data_parallel.py:32-37`): random crop 32 with padding 4, random
horizontal flip, normalize. Two implementations with identical numerics:
a vectorized NumPy path, and the C++ native module
(`native/augment.cpp`, std::thread pool, GIL released) used
automatically when it builds. `workers` (the CLI's `-j`) sets both the
native thread count and the number of batches prepared concurrently;
`prefetch` batches are staged ahead of the training loop so augmentation
overlaps the device step.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

from distributed_model_parallel_tpu import native
from distributed_model_parallel_tpu.data.datasets import ArrayDataset


def random_crop_flip(
    images: np.ndarray,
    rng: np.random.RandomState,
    padding: int = 4,
) -> np.ndarray:
    """Batched RandomCrop(pad)+RandomHorizontalFlip on uint8 NHWC,
    vectorized: one sliding-window view + one fancy-index gather, no
    per-image Python loop."""
    ys, xs, flips = _draw_augment(rng, len(images), padding)
    return _crop_flip_numpy(images, ys, xs, flips, padding)


def _draw_augment(rng: np.random.RandomState, n: int, padding: int):
    ys = rng.randint(0, 2 * padding + 1, size=n)
    xs = rng.randint(0, 2 * padding + 1, size=n)
    flips = rng.rand(n) < 0.5
    return ys, xs, flips


def _crop_flip_numpy(images, ys, xs, flips, padding):
    n, h, w, c = images.shape
    padded = np.pad(
        images,
        ((0, 0), (padding, padding), (padding, padding), (0, 0)),
        mode="constant",
    )
    # (n, 2p+1, 2p+1, c, h, w) view; gather each image's window.
    windows = np.lib.stride_tricks.sliding_window_view(
        padded, (h, w), axis=(1, 2)
    )
    out = windows[np.arange(n), ys, xs]          # (n, c, h, w)
    out = np.ascontiguousarray(out.transpose(0, 2, 3, 1))  # NHWC
    out[flips] = out[flips, :, ::-1]
    return out


def normalize(images: np.ndarray, mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    return (images.astype(np.float32) / 255.0 - mean) / std


def device_normalizer(mean: np.ndarray, std: np.ndarray):
    """The same `/255 - mean / std` normalize as a jit-traceable device
    transform, for `Engine.input_transform`. Pair with
    `Loader(device_normalize=True)`: the batch crosses the host->device
    link as uint8 (4x fewer bytes than host-normalized f32 — the link is
    the end-to-end bottleneck on a relay-attached accelerator, RESULTS
    §1c) and XLA fuses the normalize into the first conv's input."""
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)

    def transform(images):
        import jax.numpy as jnp  # keep this module importable without jax

        m = jnp.asarray(mean)
        s = jnp.asarray(std)
        return (images.astype(jnp.float32) / 255.0 - m) / s

    return transform


@dataclasses.dataclass
class Loader:
    """Deterministic, host-sharded batch iterator.

    `process_index/process_count` implement the missing DistributedSampler:
    after the global epoch shuffle (seeded by epoch, identical on all
    hosts), each host takes every `process_count`-th index. `drop_last` is
    forced on for training so batch shapes are static for XLA; with
    `drop_last=False` a ragged final batch is padded back to `batch_size`
    with label -1 rows (masked out by metrics) for the same reason.

    `batch_size` is this host's PER-HOST batch; `cli.common.build_loaders`
    divides the user-facing global batch by `jax.process_count()` before
    constructing Loaders.

    `workers` (the reference's `-j`, `model_parallel.py:31-33`) sets the
    C++ augmentation module's per-batch thread-pool size (it does not add
    Python-side concurrency; on the NumPy fallback it is a no-op).
    `prefetch` > 0 runs ONE background producer thread staging up to
    `prefetch` ready batches ahead of the training loop — with the native
    backend the augmentation call releases the GIL, so staging genuinely
    overlaps the device step. Augmentation draws are keyed by (seed,
    epoch, host, batch index), so results are identical for every
    `workers`/`prefetch` setting and for the native vs NumPy backends
    (`use_native=None` auto-detects)."""

    dataset: ArrayDataset
    batch_size: int
    shuffle: bool = True
    augment: bool = False
    mean: Optional[np.ndarray] = None
    std: Optional[np.ndarray] = None
    seed: int = 0
    process_index: int = 0
    process_count: int = 1
    drop_last: bool = True
    workers: int = 1
    prefetch: int = 2
    use_native: Optional[bool] = None  # None = auto-detect
    # Yield AUGMENTED UINT8 batches (no host normalize, no float cast):
    # the engine normalizes on device via `input_transform =
    # device_normalizer(mean, std)`. Cuts host->device bytes 4x.
    device_normalize: bool = False
    # Yield gathered batches untouched (no augment, no normalize, no
    # dtype cast) — for non-image data (token ids) where /255 would be
    # nonsense. Ragged-final-batch padding still applies.
    raw: bool = False
    # Caller-supplied per-batch transform `(arrays, labels) -> (arrays,
    # labels)` REPLACING the built-in augment/normalize — the
    # reference's compose_train/compose_val surface
    # (`dataset_collection.py:28-35`). Runs on host, before padding.
    transform: Optional[callable] = None

    def __post_init__(self):
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        if self.device_normalize and self.use_native is True:
            raise ValueError(
                "device_normalize=True conflicts with use_native=True: "
                "the native hot loop is the fused host-side "
                "augment+NORMALIZE; with device-side normalization the "
                "augmentation runs the vectorized NumPy uint8 path"
            )
        if self.use_native is True and self.mean is None:
            raise ValueError(
                "use_native=True requires mean/std (the native hot loop "
                "is the fused augment+normalize)"
            )
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        # Every host sees the same padded shard size (ceil(n/P)), so batch
        # counts agree across hosts — without this, a host with a shorter
        # shard exits its epoch loop early and the remaining hosts hang in
        # the next collective (torch's DistributedSampler pads for the same
        # reason).
        per_host = -(-len(self.dataset) // self.process_count)
        if self.drop_last:
            return per_host // self.batch_size
        return -(-per_host // self.batch_size)

    # ------------------------------------------------------------ batches

    def _native_ok(self) -> bool:
        if self.use_native is False:
            return False
        ok = native.available()
        if self.use_native is True and not ok:
            raise RuntimeError(
                "use_native=True but the native library failed to build"
            )
        return ok

    def _gather(self, idx):
        ds = self.dataset
        if hasattr(ds, "gather"):
            return ds.gather(idx)
        return ds.images[idx], ds.labels[idx]

    def _make_batch(self, b: int, idx, use_native: bool):
        """Assemble batch `b` (gather, augment, normalize, pad). Pure
        function of (seed, epoch, host, b) — order-independent by
        construction, which is what pins the determinism guarantee."""
        images, labels = self._gather(idx)
        aug_rng = np.random.RandomState(
            ((self.seed + self._epoch) * 1009 + self.process_index) * 7919
            + b
        )
        if self.transform is not None:
            images, labels = self.transform(images, labels)
        elif self.raw:
            pass  # token ids etc.: ship exactly what the dataset holds
        elif self.device_normalize:
            # Engine-side normalize: ship the (augmented) uint8 bytes.
            # The augmentation draws use the SAME keyed RNG stream, so a
            # device_normalize run sees identical crops/flips to a
            # host-normalize run of the same (seed, epoch, host, batch).
            if self.augment:
                ys, xs, flips = _draw_augment(aug_rng, len(images), 4)
                images = _crop_flip_numpy(images, ys, xs, flips, 4)
        elif self.augment:
            ys, xs, flips = _draw_augment(aug_rng, len(images), 4)
            if (use_native and self.mean is not None
                    and images.dtype == np.uint8):
                images = native.augment_normalize(
                    images, ys, xs, flips, 4, self.mean, self.std,
                    workers=self.workers,
                )
            else:
                images = _crop_flip_numpy(images, ys, xs, flips, 4)
                images = self._normalize_np(images)
        elif use_native and self.mean is not None and images.dtype == np.uint8:
            images = native.normalize(
                images, self.mean, self.std, workers=self.workers
            )
        else:
            images = self._normalize_np(images)
        if len(idx) < self.batch_size:
            # Ragged final batch (drop_last=False): pad to the static
            # batch shape so XLA never sees a second shape and the
            # 'data'-axis sharding stays divisible. Padding rows carry
            # label -1; metrics/losses mask them out (metrics.py
            # valid_count).
            pad_n = self.batch_size - len(idx)
            images = np.concatenate(
                [images, np.zeros((pad_n,) + images.shape[1:], images.dtype)]
            )
            labels = np.concatenate(
                [labels, np.full((pad_n,), -1, labels.dtype)]
            )
        return images, labels

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        n = len(self.dataset)
        rng = np.random.RandomState(self.seed + self._epoch)
        order = rng.permutation(n) if self.shuffle else np.arange(n)
        # Pad to a multiple of process_count by wrapping (DistributedSampler
        # semantics) so every host's strided shard has identical length.
        per_host = -(-n // self.process_count)
        pad = per_host * self.process_count - n
        if pad:
            # np.tile handles pad > n (tiny dataset, many hosts) — torch's
            # DistributedSampler repeats the index list the same way.
            order = np.concatenate([order, np.tile(order, -(-pad // n))[:pad]])
        mine = order[self.process_index::self.process_count]
        nb = len(self)
        use_native = self._native_ok() and self.mean is not None
        batches = (
            mine[b * self.batch_size:(b + 1) * self.batch_size]
            for b in range(nb)
        )
        indexed = (
            (b, idx) for b, idx in enumerate(batches) if len(idx) > 0
        )
        if self.prefetch <= 0:
            # Synchronous path: `workers` still sizes the native pool
            # inside each _make_batch call; there is no Python thread.
            for b, idx in indexed:
                yield self._make_batch(b, idx, use_native)
            return
        yield from self._prefetched(indexed, use_native)

    def _normalize_np(self, images):
        if self.mean is not None:
            return normalize(images, self.mean, self.std)
        return images.astype(np.float32) / 255.0

    def _prefetched(self, indexed, use_native: bool):
        """Producer thread keeps up to `prefetch` ready batches in a
        bounded queue; with the native backend the augmentation call
        releases the GIL, so production genuinely overlaps the consumer's
        device step. Batches are yielded strictly in order (determinism
        is per-batch-seeded either way). The consumer may abandon the
        iterator early (e.g. Trainer's --steps-per-epoch truncation);
        the finally block stops and joins the producer so no thread or
        staged batch outlives the epoch."""
        depth = max(self.prefetch, 1)
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        sentinel = object()
        stop = threading.Event()
        error = []

        def put_until_stop(item) -> bool:
            """Blocking put that gives up when the consumer signalled
            stop (early abandon). The SENTINEL must go through this too:
            a put_nowait sentinel can be dropped while the queue is still
            full of the last batches, deadlocking a consumer that then
            waits forever on q.get()."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for b, idx in indexed:
                    if stop.is_set():
                        return
                    if not put_until_stop(
                        self._make_batch(b, idx, use_native)
                    ):
                        return
            except BaseException as e:  # noqa: BLE001 — surfaced below
                error.append(e)
            finally:
                put_until_stop(sentinel)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            try:  # unblock a producer stuck on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=10)
        if error:
            raise error[0]
