"""Quantized decode-floor matmuls (`ops/quant_matmul.py`, ISSUE 16):
the scale-layout contract (per-output-channel weights, per-token
dynamic activations), the int8 error bound against the f32 reference,
path parity (Pallas-interpret kernel vs the dtype-pinned XLA
fallback), the jaxpr dtype records hlolint's `decode-quantized-matmul`
rule pins, and the mode/selector surfaces the engine threads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_model_parallel_tpu.ops.quant_matmul import (
    COMPUTE_DTYPES,
    QuantMatmul,
    check_compute_dtype,
    normalize_compute_dtype,
    quant_dot,
    quant_matmul,
    quantize_rows,
    quantize_weight,
)
from distributed_model_parallel_tpu.ops.wire_codec import ABSMAX_FLOOR


def _xw(seed=0, m=8, k=32, n=48):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    w = jnp.asarray(rng.randn(k, n).astype(np.float32))
    return x, w


# ------------------------------------------------------------- surface


def test_compute_dtype_surface():
    assert COMPUTE_DTYPES == ("f32", "bf16", "int8")
    for mode in COMPUTE_DTYPES:
        assert check_compute_dtype(mode) == mode
        assert normalize_compute_dtype(mode) == mode
    assert normalize_compute_dtype(None) == "f32"
    assert normalize_compute_dtype(jnp.bfloat16) == "bf16"
    assert normalize_compute_dtype(jnp.float32) == "f32"
    with pytest.raises(ValueError, match="compute_dtype"):
        check_compute_dtype("fp8")
    with pytest.raises(ValueError, match="compute_dtype"):
        normalize_compute_dtype(jnp.float16)
    with pytest.raises(ValueError, match="compute_dtype"):
        normalize_compute_dtype(object())


def test_rejects_bad_mode_and_path():
    x, w = _xw()
    with pytest.raises(ValueError, match="compute_dtype"):
        quant_matmul(x, w, "fp4")
    with pytest.raises(ValueError, match="path"):
        quant_matmul(x, w, "int8", path="cuda")


# ------------------------------------------------------- scale layout


def test_quantize_weight_per_output_channel():
    _, w = _xw(seed=1)
    wq, scale = quantize_weight(w)
    assert wq.dtype == jnp.int8 and wq.shape == w.shape
    assert scale.dtype == jnp.float32 and scale.shape == (w.shape[1],)
    np.testing.assert_allclose(
        np.asarray(scale),
        np.abs(np.asarray(w)).max(axis=0) / 127.0,
        rtol=1e-6,
    )
    # Elementwise decode bound: absmax/254 per column (module contract).
    err = np.abs(
        np.asarray(wq).astype(np.float32) * np.asarray(scale)[None, :]
        - np.asarray(w)
    )
    bound = np.abs(np.asarray(w)).max(axis=0) / 254.0
    assert (err <= bound[None, :] + 1e-7).all()


def test_quantize_weight_zero_column_decodes_exact_zero():
    w = jnp.zeros((16, 4), jnp.float32)
    wq, scale = quantize_weight(w)
    assert (np.asarray(wq) == 0).all()
    # The floored scale stays NORMAL (the wire codec's denormal guard:
    # a denormal scale would flush to zero under FTZ).
    assert (np.asarray(scale) >= np.finfo(np.float32).tiny).all()
    assert (
        np.asarray(wq).astype(np.float32) * np.asarray(scale) == 0
    ).all()


def test_quantize_rows_per_token():
    x, _ = _xw(seed=2)
    q, scale = quantize_rows(x)
    assert q.dtype == jnp.int8 and q.shape == x.shape
    assert scale.shape == (x.shape[0], 1)
    np.testing.assert_allclose(
        np.asarray(scale)[:, 0],
        np.abs(np.asarray(x)).max(axis=-1) / 127.0,
        rtol=1e-6,
    )
    err = np.abs(
        np.asarray(q).astype(np.float32) * np.asarray(scale)
        - np.asarray(x)
    )
    bound = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 254.0
    assert (err <= bound + 1e-7).all()


# ------------------------------------------------------------ the GEMM


def test_f32_mode_is_the_identity_dot():
    x, w = _xw(seed=3)
    np.testing.assert_array_equal(
        np.asarray(quant_matmul(x, w, "f32")), np.asarray(x @ w)
    )


def test_bf16_mode_casts_both_operands():
    x, w = _xw(seed=4)
    y = quant_matmul(x, w, "bf16")
    assert y.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(y),
        np.asarray(x.astype(jnp.bfloat16) @ w.astype(jnp.bfloat16)),
    )
    rel = np.abs(
        np.asarray(y, np.float32) - np.asarray(x @ w)
    ).max() / np.abs(np.asarray(x @ w)).max()
    assert rel <= 2e-2  # one bf16 rounding per operand


def test_int8_error_within_documented_budget():
    x, w = _xw(seed=5, m=32, k=64, n=48)
    ref = np.asarray(x @ w)
    y = np.asarray(quant_matmul(x, w, "int8", path="xla"))
    assert y.dtype == np.float32
    rel = np.abs(y - ref).max() / np.abs(ref).max()
    assert rel <= 2e-2, rel  # observed ~8e-3 on unit normals


def test_int8_paths_agree_and_batch_reshape():
    # Pallas kernel (interpret mode off-TPU) vs the XLA fallback, on a
    # multi-row-block shape (m=256 -> bm=128, 2 grid steps), an
    # awkward row count (m=3 -> whole-array block), and a rank-3 x.
    for m, k, n, seed in ((256, 32, 16, 6), (3, 32, 16, 7)):
        x, w = _xw(seed=seed, m=m, k=k, n=n)
        a = np.asarray(quant_matmul(x, w, "int8", path="xla"))
        b = np.asarray(
            quant_matmul(x, w, "int8", path="pallas", interpret=True)
        )
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    x, w = _xw(seed=8, m=12, k=16, n=8)
    x3 = x.reshape(3, 4, 16)
    y3 = quant_matmul(x3, w, "int8", path="xla")
    assert y3.shape == (3, 4, 8)
    np.testing.assert_array_equal(
        np.asarray(y3).reshape(12, 8),
        np.asarray(quant_matmul(x, w, "int8", path="xla")),
    )


def test_quant_dot_selector():
    assert quant_dot(None) is None
    assert quant_dot("f32") is None
    with pytest.raises(ValueError, match="compute_dtype"):
        quant_dot("fp8")
    x, w = _xw(seed=9)
    for mode in ("bf16", "int8"):
        dot = quant_dot(mode)
        np.testing.assert_array_equal(
            np.asarray(dot(x, w)),
            np.asarray(quant_matmul(x, w, mode)),
        )


def test_policy_adds_bias_in_output_dtype():
    x, w = _xw(seed=10)
    b = jnp.asarray(np.random.RandomState(11).randn(48).astype(
        np.float32
    ))
    pol = QuantMatmul(mode="int8")
    for proj in (pol.column, pol.row):
        np.testing.assert_array_equal(
            np.asarray(proj(x, w, b)),
            np.asarray(quant_matmul(x, w, "int8") + b),
        )


# ---------------------------------------------------- jaxpr dtype pins


def test_traced_dot_dtypes_are_the_lint_contract():
    """The CPU trace of each mode carries the operand dtypes hlolint's
    `decode-quantized-matmul` rule pins (`lint.jaxpr_dot_records`):
    int8 -> one s8 x s8 dot, bf16 -> one bf16 x bf16 dot, f32 -> one
    f32 x f32 dot. Compiled HLO normalizes these away; the trace must
    not."""
    from distributed_model_parallel_tpu.analysis.lint import (
        jaxpr_dot_records,
    )

    x, w = _xw(seed=12)
    want = {"f32": ("f32", "f32"), "bf16": ("bf16", "bf16"),
            "int8": ("s8", "s8")}
    for mode, pair in want.items():
        records = jaxpr_dot_records(
            lambda x, w, mode=mode: quant_matmul(
                x, w, mode, path="xla" if mode == "int8" else None
            ),
            x, w,
        )
        assert len(records) == 1
        lhs, rhs, shape = records[0]
        assert (lhs, rhs) == pair
        assert shape == (32, 48)
