"""Shared pipeline-stage partitioning for stem/blocks/head model families.

Generalizes the reference's hard-coded ws=4 rank split
(`code/distributed_training/model_parallel.py:102-104,129,143-144`:
rank 0 = stem+blocks[0:3], middle rank r = blocks[6r-3:6r+3], last =
blocks[15:]+head) to any block count and stage count. Every model family
(MobileNetV2, ResNet, ...) shares one cut-point algorithm and one stage /
pytree assembly convention, so a single-device checkpoint always loads
into the matching pipeline run and vice versa.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models import layers as L


def chunk_owner(logical: int, num_stages: int) -> int:
    """Physical stage that owns logical pipeline chunk `logical` under
    the interleaved virtual-pipeline placement (Megatron SC'21): chunks
    are dealt round-robin, so device s owns logicals {s, s+S, s+2S, ...}
    — NON-contiguous slices of the model, which is what lets a
    microbatch revisit every device V times and divide the pipeline
    bubble by V. With V=1 this is the identity (chunk i on device i)."""
    return logical % num_stages


def row_of_logical(logical: int, num_stages: int,
                   virtual_stages: int) -> int:
    """Storage row of logical chunk `logical` in the stage-local packed
    (S·V, maxP) parameter array. Rows are DEVICE-MAJOR — row s·V + v
    holds device s's v-th chunk (logical v·S + s) — so sharding the
    leading axis P('stage') lands each device's V chunks on it in local
    rows 0..V-1, matching the in-step chunk index."""
    s = logical % num_stages
    v = logical // num_stages
    return s * virtual_stages + v


def logical_of_row(row: int, num_stages: int, virtual_stages: int) -> int:
    """Inverse of `row_of_logical`."""
    s = row // virtual_stages
    v = row % virtual_stages
    return v * num_stages + s


def split_points(num_stages: int, boundaries: Sequence[int] | None,
                 n_blocks: int) -> List[int]:
    """Cut points [0, ..., n_blocks] delimiting each stage's block range.

    Default: blocks distributed as evenly as possible (earlier stages get
    the remainder). Pass `boundaries` (len num_stages-1) to override —
    e.g. [3, 9, 15] reproduces the reference's ws=4 MobileNetV2 split.
    `num_stages` counts CHUNKS: an interleaved virtual pipeline over S
    devices with V chunks each passes S·V here (the assembly convention
    is unchanged — stem on chunk 0, head on the last chunk; the ENGINE
    deals chunks round-robin to devices, `chunk_owner`).
    """
    if num_stages < 1 or num_stages > n_blocks:
        raise ValueError(f"num_stages must be in [1,{n_blocks}]")
    if boundaries is None:
        base, rem = divmod(n_blocks, num_stages)
        counts = [base + (1 if i < rem else 0) for i in range(num_stages)]
        boundaries = []
        acc = 0
        for c in counts[:-1]:
            acc += c
            boundaries.append(acc)
    if len(boundaries) != num_stages - 1:
        raise ValueError("need num_stages-1 boundaries")
    return [0, *boundaries, n_blocks]


def assemble_stages(blocks: Sequence[L.Layer], stem: L.Layer, head: L.Layer,
                    cuts: Sequence[int]) -> List[L.Layer]:
    """Stage i = blocks[cuts[i]:cuts[i+1]], with the stem prepended on
    stage 0 and the head appended on the last (the reference's
    header/medium/last roles, `model_parallel.py:99-157`)."""
    num_stages = len(cuts) - 1
    stages = []
    for i in range(num_stages):
        parts = list(blocks[cuts[i]:cuts[i + 1]])
        if i == 0:
            parts.insert(0, stem)
        if i == num_stages - 1:
            parts.append(head)
        stages.append(L.sequential(*parts))
    return stages


def stage_io_avals(stages: Sequence[L.Layer], param_avals: Sequence[Any],
                   state_avals: Sequence[Any], x_aval: Any,
                   ctx: L.Context) -> List[Tuple[Any, Any]]:
    """(input_aval, output_aval) per stage from an abstract trace — the
    static replacement for the reference's runtime dim/size handshake
    (`distributed_layers.py:40-47`), and the metadata every pipeline
    schedule sizes its buffers from: the GPipe wire buffer is the max
    output size, and the 1F1B activation ring holds per-stage *inputs*,
    so ring sizing needs the input avals too (stage 0's input is the
    image microbatch, which never rides the wire). Stage I/O may be any
    pytree of arrays (e.g. BERT's (hidden, mask) pair)."""
    avals = []
    aval = x_aval
    for i, stage in enumerate(stages):
        out = jax.eval_shape(
            lambda p, s, x, stage=stage: stage.apply(p, s, x, ctx)[0],
            param_avals[i], state_avals[i], aval,
        )
        avals.append((aval, out))
        aval = out
    return avals


def partition_tree(tree: Any, cuts: Sequence[int]) -> List[dict]:
    """Map a full-model `{stem, blocks:{'0'..}, head}` params/state pytree
    onto the `assemble_stages` structure (sequential-keyed stage trees in
    the same part order)."""
    num_stages = len(cuts) - 1
    out = []
    for i in range(num_stages):
        parts = []
        if i == 0:
            parts.append(tree["stem"])
        parts.extend(tree["blocks"][str(b)] for b in range(cuts[i], cuts[i + 1]))
        if i == num_stages - 1:
            parts.append(tree["head"])
        out.append({str(j): p for j, p in enumerate(parts)})
    return out


def stack_block_params(blocks_tree: dict, n_blocks: int) -> Any:
    """Stack the `{'0'.., str(n_blocks-1)}` per-block param subtrees
    (identical structure by construction — the uniform-block model
    families) along a new leading block axis. The composed-plan engine
    (`parallel/plan.py`) slices this stacked tensor by stage index so
    every device runs ONE shared block apply over its contiguous slice
    — the uniform-program counterpart of `partition_tree`'s per-stage
    cut trees (which allow uneven cuts but produce per-stage
    structures a single traced program cannot select among)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[blocks_tree[str(j)] for j in range(n_blocks)],
    )


def unpartition_tree(stage_trees: Sequence[dict],
                     cuts: Sequence[int]) -> dict:
    """Inverse of `partition_tree`: reassemble per-stage sequential-keyed
    trees into the full-model `{stem, blocks:{'0'..}, head}` layout, so a
    stagewise backward hands the optimizer a gradient pytree
    indistinguishable from the monolithic `jax.grad`'s."""
    num_stages = len(cuts) - 1
    out: dict = {"blocks": {}}
    for i, stage in enumerate(stage_trees):
        k = 0
        if i == 0:
            out["stem"] = stage[str(k)]
            k += 1
        for b in range(cuts[i], cuts[i + 1]):
            out["blocks"][str(b)] = stage[str(k)]
            k += 1
        if i == num_stages - 1:
            out["head"] = stage[str(k)]
    return out


# ------------------------------------------------- stagewise backward
# The overlapped-reducer substrate (`grad_reduction="overlapped"` on the
# DDP/FSDP/CausalLM-SP engines): instead of one `jax.grad` over the whole
# model — whose gradient pytree exists only after the LAST backward op —
# the forward is cut at the same block boundaries the pipeline engines
# use (`split_points`), one `jax.vjp` closure saved per segment, and the
# closures are called in REVERSE (late layers first). Stage k's parameter
# gradients are therefore complete — and can be handed to the bucketed
# ring reduction (`ops/grad_reduction.py`) — while stage k-1's backward
# has not produced a single op that the reduction depends on, which is
# exactly the data-dependence structure the DDP Reducer's autograd hooks
# buy (Li et al., VLDB 2020; PAPERS.md).


@dataclasses.dataclass(frozen=True)
class StageParts:
    """The stem/blocks/head anatomy of a composed model, attached to the
    built `Layer` by `staged_model` so engines can re-cut the SAME layer
    objects (same params layout, same `Context.child` rng folding) into
    backward segments without re-building the model."""

    stem: L.Layer
    blocks: Tuple[L.Layer, ...]
    head: L.Layer


def staged_model(stem: L.Layer, blocks: Sequence[L.Layer],
                 head: L.Layer) -> L.Layer:
    """Compose the canonical `named([stem, blocks, head])` model AND
    attach its `StageParts` — the one constructor the model zoo's
    stem/blocks/head families share, so every one of them is eligible
    for the stagewise-backward engines."""
    model = L.named([
        ("stem", stem),
        ("blocks", L.sequential(*blocks)),
        ("head", head),
    ])
    return dataclasses.replace(
        model, parts=StageParts(stem, tuple(blocks), head)
    )


def resolve_overlap_segments(n_blocks: int, overlap_stages: int,
                             label: str, noun: str = "blocks") -> int:
    """Validate-and-default the stagewise segment count shared by every
    overlapped engine: 0 = auto (min(4, n_blocks)); otherwise the count
    must give >= 2 segments and <= one block per segment. Raises with
    engine vocabulary (`label` names the knob's surface, `noun` the
    unit being cut)."""
    if n_blocks < 2:
        raise ValueError(
            f"{label}: grad_reduction='overlapped' splits the backward "
            f"into >= 2 segments; the model has only {n_blocks} "
            f"{noun[:-1]}(s)"
        )
    if overlap_stages == 0:
        return min(4, n_blocks)
    if overlap_stages < 2 or overlap_stages > n_blocks:
        raise ValueError(
            f"{label}: overlap_stages must be in [2, {n_blocks}] "
            f"({noun}), got {overlap_stages}"
        )
    return overlap_stages


def resolve_overlap_stages(parts: Optional[StageParts],
                           overlap_stages: int, label: str) -> int:
    """`resolve_overlap_segments` over a `StageParts` anatomy (the
    stem/blocks/head engines' entry point; raises when the model never
    went through `staged_model`)."""
    if parts is None:
        raise ValueError(
            f"{label}: grad_reduction='overlapped' needs a model that "
            "exposes its stem/blocks/head anatomy "
            "(models/staging.staged_model); this model has no .parts"
        )
    return resolve_overlap_segments(
        len(parts.blocks), overlap_stages, label
    )


def stage_apply_fns(parts: StageParts, cuts: Sequence[int],
                    ctx: L.Context) -> List[Callable]:
    """Per-stage apply closures over `partition_tree`-layout stage trees.

    Each closure `fn(stage_params, stage_state, x) -> (y, new_state)`
    applies its slice of the model with the SAME `Context.child` chain
    the composed `staged_model` layer uses (stem -> ctx.child(0), block
    j -> ctx.child(1).child(j), head -> ctx.child(2)), so the stagewise
    forward/backward is bit-identical to the monolithic one — including
    dropout masks, which fold the global child indices into the rng."""
    num_stages = len(cuts) - 1
    block_ctx = ctx.child(1)
    fns = []
    for i in range(num_stages):
        entries = []
        if i == 0:
            entries.append((parts.stem, ctx.child(0)))
        for j in range(cuts[i], cuts[i + 1]):
            entries.append((parts.blocks[j], block_ctx.child(j)))
        if i == num_stages - 1:
            entries.append((parts.head, ctx.child(2)))

        def fn(params, state, x, entries=entries):
            new_state = {}
            for k, (layer, c) in enumerate(entries):
                x, s = layer.apply(params[str(k)], state[str(k)], x, c)
                new_state[str(k)] = s
            return x, new_state

        fns.append(fn)
    return fns


def stagewise_value_and_grad(
    stage_fns: Sequence[Callable],
    loss_fn: Callable,
    stage_params: Sequence[Any],
    stage_states: Sequence[Any],
    x: Any,
    *,
    aux_of_state: Optional[Callable] = None,
    on_stage_grads: Optional[Callable] = None,
):
    """Segment-by-segment value-and-grad: chain per-stage `jax.vjp`
    closures in reverse, late layers first.

    `stage_fns[k](params_k, state_k, x) -> (y, new_state_k)`;
    `loss_fn(y_last) -> (loss, loss_aux)` (the scalar is differentiated).
    Differentiable side-penalties riding the state (`moe_aux`) enter
    through `aux_of_state(new_state_k) -> scalar`, whose unit cotangent
    adds each stage's d(aux)/d(params) exactly as a monolithic
    `loss + sum(aux)` grad would.

    `on_stage_grads(k, grads_k)` is the Reducer hook: it runs as soon as
    stage k's backward closure returns, BEFORE stage k-1's backward is
    traced, so whatever collectives it issues are data-dependent only on
    stages >= k. Returns (loss, loss_aux, stage_grads, stage_new_states)
    — grads in `partition_tree` stage layout (reassemble with
    `unpartition_tree`); equals the monolithic `jax.grad` bit for bit
    (tests/test_grad_reduction.py)."""
    n = len(stage_fns)
    vjps, auxes, new_states = [], [], []
    y = x
    for k in range(n):
        def fwd(p, xx, k=k):
            out, ns = stage_fns[k](p, stage_states[k], xx)
            a = aux_of_state(ns) if aux_of_state is not None else None
            return (out, a), ns

        with jax.named_scope(f"fwd_stage{k}"):
            (y, a), vjp_fn, ns = jax.vjp(
                fwd, stage_params[k], y, has_aux=True
            )
        vjps.append(vjp_fn)
        auxes.append(a)
        new_states.append(ns)
    with jax.named_scope("loss_head"):
        loss, loss_vjp, loss_aux = jax.vjp(loss_fn, y, has_aux=True)
        cot = loss_vjp(jnp.ones_like(loss))[0]
    grads: List[Any] = [None] * n
    for k in reversed(range(n)):
        with jax.named_scope(f"bwd_stage{k}"):
            a_bar = None if auxes[k] is None else jnp.ones_like(auxes[k])
            dp, dx = vjps[k]((cot, a_bar))
        grads[k] = dp if on_stage_grads is None else on_stage_grads(k, dp)
        cot = dx
    return loss, loss_aux, grads, new_states
