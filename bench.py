"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric: MobileNetV2 CIFAR-10 data-parallel training throughput
(images/sec across the whole mesh), the exact workload behind the
reference's only published performance table: `nn.DataParallel`, batch 512,
0.396 s/batch on 4 GPUs = 1292.9 images/sec (`Readme.md:283-287`,
SURVEY.md §6). `vs_baseline` is our images/sec divided by that number.

Runs on whatever devices are present (one real TPU chip under the driver;
the virtual CPU mesh if JAX_PLATFORMS=cpu is forced).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.mobilenetv2 import mobilenet_v2
from distributed_model_parallel_tpu.parallel.data_parallel import DataParallelEngine
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD

# Reference: DP 0.396 s/batch @ global batch 512 on 4 GPUs (Readme.md:283-287).
BASELINE_IMG_PER_SEC = 512 / 0.396

BATCH = 512
WARMUP = 5
ITERS = 30


def main() -> None:
    mesh = make_mesh(MeshSpec(data=-1))
    engine = DataParallelEngine(
        model=mobilenet_v2(10), optimizer=SGD(), mesh=mesh
    )
    state = engine.init_state(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    images = rng.rand(BATCH, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(BATCH,)).astype(np.int32)
    images, labels = engine.shard_batch(images, labels)
    lr = jnp.float32(0.2)

    for _ in range(WARMUP):
        state, metrics = engine.train_step(state, images, labels, lr)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, metrics = engine.train_step(state, images, labels, lr)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    img_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "mobilenetv2_cifar10_dp_train_throughput",
        "value": round(img_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
