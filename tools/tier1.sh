#!/usr/bin/env bash
# Tier-1 verify — THE canonical test command (ROADMAP.md "Tier-1
# verify"). Checked in so builder and reviewer run the same line instead
# of copy-pasting divergent variants.
#
#   bash tools/tier1.sh            # from the repo root
#
# Behavior, matching the ROADMAP line (the only additions are the
# --durations flags, which append a report section pytest's dot
# protocol and our DOTS_PASSED grep never see):
#   * CPU-only jax (the conftest also forces it; the env var keeps the
#     PJRT plugin from dialing the TPU relay at interpreter start),
#   * the default marker filter (-m 'not slow', see pytest.ini) — the
#     full S×V×M pipeline-schedule parity sweep is `slow`; tier-1 keeps
#     its S=2,V=2,M=4 smoke case,
#   * a fast `--collect-only` PRE-GATE so import/collection errors fail
#     in seconds with the module named (exit 2), instead of surfacing
#     mid-run; the main pass still carries
#     --continue-on-collection-errors as a belt-and-braces backstop,
#   * an `hlolint` PRE-GATE (tools/hlolint --pregate, exit 3): the
#     collective-contract linter over tinycnn DDP/FSDP overlapped plus
#     the tinycnn-sized hierarchical-MoE combo, so a broken
#     ring/fabric/overlap/dispatch contract fails in seconds with the
#     violated rule named (INTERNALS.md section 8b has the catalog),
#   * costgate / obsreport / plangate PRE-GATES (exits 4/5/6): the
#     static cost ledger, the golden run report, and the auto-tuner's
#     committed plan grid, each failing with the combo/line/cell named,
#   * 870 s budget with a hard kill 10 s later,
#   * DOTS_PASSED=<n> printed from the progress dots as a
#     tamper-resistant pass count (parsed from the tee'd log, not from
#     pytest's summary line),
#   * a per-module slowest-10 durations digest (from pytest's
#     --durations section) so a module creeping toward the 870 s budget
#     is visible in every run, not just the ones that blow it, with an
#     explicit WARNING line for any module whose >=0.5s tests total
#     more than 120 s (the budget-rebalance trigger: such a module is
#     the next candidate for a slow demotion with a tier-1 twin),
#   * exits with pytest's status (PIPESTATUS survives the tee).

set -o pipefail
cd "$(dirname "$0")/.."

# Collection pre-gate: a broken import/collect error should fail the
# gate in SECONDS-not-minutes with the offending module named, instead
# of surfacing mid-run (or hiding behind
# --continue-on-collection-errors in the main pass). --collect-only
# runs no tests; the budget covers importing every test module on this
# 1-core host (~90 s, jax import dominates).
rm -f /tmp/_t1_collect.log
if ! timeout -k 5 240 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --collect-only \
    -p no:cacheprovider > /tmp/_t1_collect.log 2>&1; then
  echo "[tier1] COLLECTION FAILED — fix imports before the suite runs:"
  tail -40 /tmp/_t1_collect.log
  echo DOTS_PASSED=0
  exit 2
fi
echo "[tier1] collection ok:" \
  "$(grep -cE '::' /tmp/_t1_collect.log || true) tests collected"

# hlolint pre-gate (mirrors the --collect-only pre-gate): lint the
# deepest-rule-stack combos (tinycnn DDP + FSDP overlapped — rings,
# overlap deps, BN allowlist, at-rest sharding — plus the tinycnn-sized
# hierarchical-MoE dispatch combo, the tinycnn-sized quantized-dcn
# combo so a broken wire codec fails with dcn-compressed-payload
# named, and the speculative paged+ringed serve combo so a verify step
# that falls off the rings fails with spec-verify-step named) BEFORE
# the suite, so a broken collective contract fails in seconds with the
# violated rule NAMED instead of as a slow structural-test failure
# mid-run. Exit 3 distinguishes a contract violation from a collection
# failure (2).
rm -f /tmp/_t1_hlolint.log
if ! timeout -k 5 300 bash tools/hlolint --pregate \
    > /tmp/_t1_hlolint.log 2>&1; then
  echo "[tier1] HLOLINT PRE-GATE FAILED — a collective contract is" \
    "violated (tools/hlolint, INTERNALS.md section 8b):"
  grep -aE "ERROR|WARN|LOWERING FAILED|hlo_lint" /tmp/_t1_hlolint.log \
    | head -20
  echo DOTS_PASSED=0
  exit 3
fi
echo "[tier1] hlolint pre-gate ok:" \
  "$(grep -ac '"partial": true' /tmp/_t1_hlolint.log || true)" \
  "combo(s) lint clean"

# costgate pre-gate (the perf twin of the hlolint pre-gate): the
# static cost engine re-prices the tier-1 combo cut against the
# committed ledger (experiments/cost_ledger.json) and name-checks
# every full-matrix combo for ledger coverage — a combo whose
# predicted step time regressed past tolerance, or a new combo shipped
# without a cost baseline, fails in seconds with the combo NAMED.
# Exit 4 distinguishes a cost regression from a contract violation (3)
# and a collection failure (2).
rm -f /tmp/_t1_costgate.log
if ! timeout -k 5 300 bash tools/costgate --pregate \
    > /tmp/_t1_costgate.log 2>&1; then
  echo "[tier1] COSTGATE PRE-GATE FAILED — a combo's predicted step" \
    "time regressed or lacks a ledger row (tools/costgate," \
    "INTERNALS.md section 13):"
  grep -aE "FAIL|costgate" /tmp/_t1_costgate.log | head -20
  echo DOTS_PASSED=0
  exit 4
fi
echo "[tier1] costgate pre-gate ok:" \
  "$(grep -ac '"partial": true' /tmp/_t1_costgate.log || true)" \
  "combo(s) priced within tolerance"

# plangate pre-gate (the auto-tuner twin of the costgate pre-gate):
# re-run the deterministic knob search for the tier-1 cell cut
# (tinycnn DDP + the hierarchical-MoE cell) and compare argmin knobs +
# predicted step time against the committed
# experiments/tuned_plans.json, name-checking every grid cell — a
# drifted argmin (the cost landscape moved under an engine change) or
# a plan-less cell fails in seconds with the cell NAMED. Exit 6
# distinguishes a plan drift from a report regression (5), a cost
# regression (4), a contract violation (3) and a collection failure
# (2).
rm -f /tmp/_t1_plangate.log
if ! timeout -k 5 420 bash tools/plangate --pregate \
    > /tmp/_t1_plangate.log 2>&1; then
  echo "[tier1] PLANGATE PRE-GATE FAILED — a tuned plan's argmin or" \
    "predicted time drifted (tools/plangate, INTERNALS.md section 15):"
  grep -aE "FAIL|plangate" /tmp/_t1_plangate.log | head -20
  echo DOTS_PASSED=0
  exit 6
fi
echo "[tier1] plangate pre-gate ok:" \
  "$(grep -ac '"partial": true' /tmp/_t1_plangate.log || true)" \
  "cell(s) re-searched within tolerance"

# obsreport pre-gate (the measured twin of the costgate pre-gate):
# render the canned golden trace + metrics + ledger through the
# jax-free report pipeline (observability/report.py) and byte-compare
# against tests/golden/obsreport_report.txt — broken attribution /
# quantile / reconciliation semantics fail in under a second with the
# first diverging line printed. Exit 5 distinguishes a report
# regression from a cost regression (4), a contract violation (3) and
# a collection failure (2).
rm -f /tmp/_t1_obsreport.log
if ! timeout -k 5 60 bash tools/obsreport --pregate \
    > /tmp/_t1_obsreport.log 2>&1; then
  echo "[tier1] OBSREPORT PRE-GATE FAILED — the golden run report" \
    "drifted (tools/obsreport, INTERNALS.md section 14):"
  grep -aE "FAIL|obsreport|want:|got:" /tmp/_t1_obsreport.log | head -20
  echo DOTS_PASSED=0
  exit 5
fi
echo "[tier1] obsreport pre-gate ok:" \
  "$(grep -aco '"pregate": "ok"' /tmp/_t1_obsreport.log || true)" \
  "golden report byte-stable"

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    --durations=0 --durations-min=0.5 \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)

# Per-module slowest-10 digest from the durations section ("1.23s call
# tests/test_x.py::test_y" lines). Purely informational: never changes rc.
python - <<'PYEOF' || true
import collections
import re

rows = collections.defaultdict(list)
try:
    with open("/tmp/_t1.log") as f:
        for line in f:
            m = re.match(
                r"\s*([0-9.]+)s\s+call\s+(tests/[^:]+)::(\S+)", line
            )
            if m:
                rows[m.group(2)].append((float(m.group(1)), m.group(3)))
except OSError:
    rows = {}
for mod in sorted(rows, key=lambda k: -sum(s for s, _ in rows[k])):
    top = sorted(rows[mod], reverse=True)[:10]
    total = sum(s for s, _ in rows[mod])
    print(f"[tier1-durations] {mod} ({total:.1f}s in >=0.5s tests) "
          f"slowest-{len(top)}: "
          + ", ".join(f"{name}={secs:.1f}s" for secs, name in top))
    if total > 120:
        print(f"[tier1-durations] WARNING: {mod} exceeds 120s "
              f"({total:.1f}s) — candidate for a slow demotion with a "
              f"tier-1 twin (budget-rebalance convention)")
PYEOF

exit $rc
