"""Static analysis over lowered/compiled HLO — the collective-contract
linter.

PRs 2-5 earned their perf claims structurally: HLO pins asserting ring
shapes (S-1 permutes per collective-matmul ring, 2(S-1) per bucket),
fabric routing (no grad-sized all-reduce over 'dcn'), and overlap
dependency freedom (first-fired bucket independent of stage-0 backward).
That machinery lived as private helpers inside individual test files and
covered only the combos someone hand-wrote a pin for. This package
promotes it to a first-class subsystem:

  hlo.py          text -> instruction-graph model (computations,
                  instructions, operands, called computations,
                  named-scope tags, replica groups, shapes/dtypes/bytes,
                  conservative transitive reachability)
  collectives.py  classify every collective: kind, payload bytes,
                  ring-vs-monolithic, and which mesh fabric it crosses
                  ('ici' vs 'dcn') by mapping replica groups back
                  through the mesh device array
  rules.py        declarative registry of severity-tagged rules encoding
                  the contracts the repo claims in prose (INTERNALS §8b
                  catalogs them)
  lint.py         lower any engine x model x mode combo on a virtual
                  mesh and run the registry over it; `tools/hlolint` is
                  the CLI

The tests (tests/test_collectives_hlo.py and friends) import this
library instead of carrying private parsers; tests/test_hlolint.py lints
the engine matrix so a future engine change that breaks a contract fails
with a named rule, not a silent perf regression.
"""
