"""Fused flash-attention kernels in Pallas (TPU) — forward AND backward.

The attention hot op, tiled for the MXU with online softmax so the
(Tq, Tkv) logits matrix never materializes in HBM: the grid streams
(block_q x block_k) tiles, q@k^T runs on the MXU in f32, and the running
max / denominator / numerator live in VMEM scratch across the k-block
grid steps (TPU grids iterate the last axis innermost, and scratch
persists across steps — the canonical Pallas flash pattern).

Backward is fused too: the forward saves only the per-row logsumexp
(LSE — O(T), not O(T²)); the backward recomputes each (bq, bk)
probability tile from q/k/LSE in VMEM and accumulates
  dq += scale · dS @ K       (one kernel, k-blocks innermost)
  dv += Pᵀ @ dO,  dk += scale · dSᵀ @ Q   (one kernel, q-blocks innermost)
with dS = P ∘ (dO @ Vᵀ − Δ), Δ = rowsum(dO ∘ O) computed cheaply in XLA.
Nothing O(T²) ever leaves VMEM in either direction.

TPU layout notes (Mosaic requires a block's last two dims to be
(8k, 128k) multiples or to equal the array dims):
* Per-row stats (LSE, Δ) are stored lane-broadcast as (B, H, Tq, 128)
  f32 — the same layout the reference TPU flash kernels use — so their
  (1, 1, bq, 128) blocks tile legally; kernels read lane 0.
* The (B, Tkv) key-validity mask is reshaped (B, 1, Tkv) and each grid
  step loads the whole row, slicing its (bk,) window with `pl.dslice`
  — legal for every block size, and a Tkv-byte row of int8 is free.

Contract and scope:
* Same contract as `dot_product_attention`: (B, T, H, Dh) tensors,
  optional (B, Tkv) key-validity mask, `causal=True` for decoder models,
  computes f32, returns q.dtype.
* Sequence lengths must divide the block sizes (the wrapper shrinks
  blocks to fit when the sequence is shorter); lengths with no
  multiple-of-8 divisor >= 8 fall back to the XLA path — forward and
  backward stay consistent either way. Composes with ring / Ulysses
  sequence parallelism, which shard T across chips before any kernel
  runs.
* On non-TPU backends the kernels run in Pallas interpret mode (slow,
  CI-only) so the numerics are testable on the 8-virtual-device mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - exotic builds
    pltpu = None
    _VMEM = None

from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)

_NEG = jnp.finfo(jnp.float32).min
_LANES = 128  # lane-broadcast width for per-row stats (see module doc)
# v5e-tuned default tiles (see flash_attention docstring); shared with
# the ring_flash per-hop dispatch so a retune applies everywhere.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _mask_window(mask_ref, ki: int, bk: int):
    """(1, bk) bool validity window from the whole-row (1, 1, Tkv) mask.
    Kept rank-2 — Mosaic's vector layouts want >= 2D operands."""
    if mask_ref is None:
        return None
    return mask_ref[0, :, pl.dslice(ki * bk, bk)] != 0


def _tile_logits(q, k, scale, valid, causal, qi, ki, bq, bk):
    """One (bq, bk) logits tile: scale·q@kᵀ with mask/causal applied —
    shared by the forward recurrence and both backward kernels so the
    recomputed probabilities match the saved LSE bit-for-bit.

    q/k stay in their storage dtype (bf16 inputs hit the MXU's native
    bf16 path — ~4x the f32 matmul rate on v5e) with f32 accumulation;
    the scale is applied to the f32 product, exactly."""
    s = scale * lax.dot_general(  # (bq, bk) on the MXU
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if valid is not None:  # static: masked kernel variant only
        s = jnp.where(valid, s, _NEG)  # valid is (1, bk), broadcasts
    if causal:  # global row >= global col within this tile pair
        rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, _NEG)
    return s


def _rows(ref):
    """Lane-0 column of a lane-broadcast (1, 1, bq, 128) stats block ->
    (bq, 1)."""
    return ref[0, 0][:, 0:1]


def _flash_step(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, scale: float, nk: int,
                causal: bool = False):
    ki = pl.program_id(3)
    qi = pl.program_id(2)  # hoisted: program_id may not be called inside
    bq = q_ref.shape[2]    # the pl.when branch (no lowering rule there)
    bk = k_ref.shape[2]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr[:], _NEG)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    def compute():
        q = q_ref[0, 0]                                  # (bq, dh)
        k = k_ref[0, 0]                                  # (bk, dh)
        v = v_ref[0, 0]                                  # (bk, dh)
        valid = _mask_window(mask_ref, ki, bk)
        s = _tile_logits(q, k, scale, valid, causal, qi, ki, bq, bk)

        m_prev = m_scr[:]                                # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                           # (bq, bk) f32
        if valid is not None or causal:
            # exp(_NEG - m_new) underflows to 0 for any finite m_new, but
            # a row that is masked in EVERY tile so far has m_new == _NEG
            # and would get p == exp(0) == 1 on its masked entries; zero
            # them explicitly so l stays 0 and finalize emits out == 0.
            p = jnp.where(s == _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)                   # (bq, 1)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        # p rides the MXU in the value dtype (bf16 for bf16 models —
        # p in [0,1] loses nothing material); accumulation stays f32.
        acc_scr[:] = acc_scr[:] * corr + lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    if causal:
        # Skip tiles strictly above the causal frontier: their logits
        # would all be _NEG and contribute nothing, but the MXU work and
        # K/V DMA are ~half the grid at long T — predicate them away.
        pl.when(ki * bk <= qi * bq + bq - 1)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:]                                     # (bq, 1)
        denom = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        if lse_ref is not None:
            # Per-row logsumexp, the only O(T) residual the backward
            # needs. Fully-masked rows (l == 0) store +inf so the
            # backward's exp(s - lse) recomputes p == 0 => zero
            # gradients, matching the forward's zero output there.
            lse = jnp.where(l > 0, m_scr[:] + jnp.log(denom), jnp.inf)
            lse_ref[0, 0] = lax.broadcast_in_dim(
                lse, lse_ref.shape[2:], (0, 1)
            )


def _fwd_kernel(*refs, scale: float, nk: int, causal: bool,
                has_mask: bool, with_lse: bool):
    """Shared forward kernel body; operand list is
    q, k, v[, mask], o[, lse], m_scr, l_scr, acc_scr — the mask row and
    the LSE output are static build-time options (inference drops LSE so
    the opaque pallas_call never writes a residual nothing reads)."""
    i = 3
    mask_ref = refs[i] if has_mask else None
    i += int(has_mask)
    o_ref = refs[i]
    lse_ref = refs[i + 1] if with_lse else None
    m_scr, l_scr, acc_scr = refs[-3:]
    _flash_step(refs[0], refs[1], refs[2], mask_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, scale, nk, causal)


def _pick_block(t: int, want: int) -> int:
    """Largest multiple-of-8 divisor of `t` that is <= want (block shapes
    must tile the sequence exactly; Mosaic wants sublane multiples of 8).
    Returns 0 when none exists."""
    b = min(want, t)
    while b >= 8:
        if t % b == 0 and b % 8 == 0:
            return b
        b -= 1
    return 0


def _blocks_viable(tq: int, tk: int, block_q: int, block_k: int):
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    # Awkward sequence lengths (primes, odd) have no viable tiling — a
    # silent performance cliff and a Mosaic lowering error. The XLA path
    # is the better program there.
    return (bq, bk) if bq and bk else None


def _row_stats_spec(bq):
    return pl.BlockSpec(
        (1, 1, bq, _LANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )


def _whole_mask_spec(tk):
    return pl.BlockSpec((1, 1, tk), lambda bi, hi, qi, ki: (bi, 0, 0))


def _flash_forward(q, k, v, mask, scale, block_q, block_k, interpret,
                   causal=False, need_lse=False):
    """Returns (out, lse) from the fused kernel — lse lane-broadcast as
    (B, H, Tq, 128), or None unless `need_lse` (the vjp forward) — or
    (xla_out, None) on the small-block fallback."""
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    blocks = _blocks_viable(tq, tk, block_q, block_k)
    if blocks is None:
        return dot_product_attention(
            q, k, v, mask, scale=scale, causal=causal
        ), None
    bq, bk = blocks
    nq, nk = tq // bq, tk // bk

    # (B, H, T, Dh) layout for clean (seq, head_dim) blocks.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    qspec = pl.BlockSpec((1, 1, bq, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kspec = pl.BlockSpec((1, 1, bk, dh), lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    operands = [qt, kt, vt]
    in_specs = [qspec, kspec, kspec]
    if mask is not None:
        operands.append(mask.astype(jnp.int8)[:, None, :])
        in_specs.append(_whole_mask_spec(tk))
    kernel = functools.partial(
        _fwd_kernel, scale=scale, nk=nk, causal=causal,
        has_mask=mask is not None, with_lse=need_lse,
    )
    out_specs = [
        pl.BlockSpec((1, 1, bq, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    ]
    out_shape = [jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype)]
    if need_lse:
        out_specs.append(_row_stats_spec(bq))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, tq, _LANES), jnp.float32)
        )
    res = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _VMEM((bq, 1), jnp.float32),   # running max
            _VMEM((bq, 1), jnp.float32),   # running denominator
            _VMEM((bq, dh), jnp.float32),  # running numerator
        ],
        interpret=interpret,
    )(*operands)
    if need_lse:
        out, lse = res
    else:
        (out,), lse = res, None
    return jnp.transpose(out, (0, 2, 1, 3)), lse


# ------------------------------------------------------------- backward


def _bwd_dq_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                 dq_ref, dq_scr, scale: float, nk: int, causal: bool):
    ki = pl.program_id(3)
    qi = pl.program_id(2)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr[:])

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        valid = _mask_window(mask_ref, ki, bk)
        s = _tile_logits(q, k, scale, valid, causal, qi, ki, bq, bk)
        p = jnp.exp(s - _rows(lse_ref))                  # (bq, bk) f32
        dp = lax.dot_general(                            # dO @ Vᵀ
            do, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - _rows(delta_ref))                 # f32
        dq_scr[:] = dq_scr[:] + scale * lax.dot_general(
            ds.astype(k.dtype), k,  # MXU-native dtype, f32 accumulate
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(ki * bk <= qi * bq + bq - 1)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                  dk_ref, dv_ref, dk_scr, dv_scr,
                  scale: float, nq: int, causal: bool):
    qi = pl.program_id(3)  # q-blocks innermost in this kernel
    ki = pl.program_id(2)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr[:])
        dv_scr[:] = jnp.zeros_like(dv_scr[:])

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        valid = _mask_window(mask_ref, ki, bk)
        s = _tile_logits(q, k, scale, valid, causal, qi, ki, bq, bk)
        p = jnp.exp(s - _rows(lse_ref))                  # (bq, bk) f32
        dv_scr[:] = dv_scr[:] + lax.dot_general(         # Pᵀ @ dO
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(                            # dO @ Vᵀ
            do, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - _rows(delta_ref))                 # f32
        dk_scr[:] = dk_scr[:] + scale * lax.dot_general(  # dSᵀ @ Q
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Only q blocks at or below the causal frontier of this k block
        # contribute; earlier q blocks see an all-masked tile.
        pl.when(qi * bq + bq - 1 >= ki * bk)(compute)
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, mask, out, lse, g, scale, bq, bk,
                    interpret, causal):
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    nq, nk = tq // bq, tk // bk

    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))
    # Δ_i = Σ_d dO_id · O_id — O(B·H·T·Dh) elementwise work; XLA fuses
    # this more cheaply than a kernel would. Lane-broadcast like LSE.
    delta = jnp.broadcast_to(
        jnp.sum(
            dot.astype(jnp.float32)
            * jnp.transpose(out, (0, 2, 1, 3)).astype(jnp.float32),
            axis=-1, keepdims=True,
        ),
        (b, h, tq, _LANES),
    )

    mask3 = None if mask is None else mask.astype(jnp.int8)[:, None, :]

    # dq: iterate k blocks innermost, accumulate into a (bq, dh) scratch.
    qspec = pl.BlockSpec(
        (1, 1, bq, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )
    kspec = pl.BlockSpec(
        (1, 1, bk, dh), lambda bi, hi, qi, ki: (bi, hi, ki, 0)
    )
    dq_ops = [qt, kt, vt, dot, lse, delta]
    dq_specs = [qspec, kspec, kspec, qspec, _row_stats_spec(bq),
                _row_stats_spec(bq)]
    if mask3 is not None:
        dq_ops.append(mask3)
        dq_specs.append(_whole_mask_spec(tk))

    def dq_kernel(*refs):
        if mask3 is not None:
            q_r, k_r, v_r, do_r, lse_r, dl_r, m_r, dq_r, scr = refs
        else:
            (q_r, k_r, v_r, do_r, lse_r, dl_r, dq_r, scr), m_r = refs, None
        _bwd_dq_step(q_r, k_r, v_r, do_r, lse_r, dl_r, m_r, dq_r, scr,
                     scale, nk, causal)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=dq_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
        scratch_shapes=[_VMEM((bq, dh), jnp.float32)],
        interpret=interpret,
    )(*dq_ops)

    # dk/dv: iterate q blocks innermost (note the swapped grid axes — the
    # index maps below read grid position 2 as ki, 3 as qi).
    kv_qspec = pl.BlockSpec(
        (1, 1, bq, dh), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    )
    kv_kspec = pl.BlockSpec(
        (1, 1, bk, dh), lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    )
    kv_rowq = pl.BlockSpec(
        (1, 1, bq, _LANES), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    )
    dkv_ops = [qt, kt, vt, dot, lse, delta]
    dkv_specs = [kv_qspec, kv_kspec, kv_kspec, kv_qspec, kv_rowq, kv_rowq]
    if mask3 is not None:
        dkv_ops.append(mask3)
        # _whole_mask_spec's index map ignores the two block grid axes,
        # so it is correct here despite this kernel's swapped grid.
        dkv_specs.append(_whole_mask_spec(tk))

    def dkv_kernel(*refs):
        if mask3 is not None:
            (q_r, k_r, v_r, do_r, lse_r, dl_r, m_r,
             dk_r, dv_r, kscr, vscr) = refs
        else:
            (q_r, k_r, v_r, do_r, lse_r, dl_r,
             dk_r, dv_r, kscr, vscr), m_r = refs, None
        _bwd_dkv_step(q_r, k_r, v_r, do_r, lse_r, dl_r, m_r,
                      dk_r, dv_r, kscr, vscr, scale, nq, causal)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, nk, nq),
        in_specs=dkv_specs,
        out_specs=[kv_kspec, kv_kspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk, dh), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, dh), v.dtype),
        ],
        scratch_shapes=[
            _VMEM((bk, dh), jnp.float32),
            _VMEM((bk, dh), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_ops)

    to_bthd = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    return to_bthd(dq), to_bthd(dk), to_bthd(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, scale, block_q, block_k, interpret, causal):
    out, _ = _flash_forward(
        q, k, v, mask, scale, block_q, block_k, interpret, causal
    )
    return out


def _flash_fwd(q, k, v, mask, scale, block_q, block_k, interpret, causal):
    out, lse = _flash_forward(
        q, k, v, mask, scale, block_q, block_k, interpret, causal,
        need_lse=True,
    )
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(scale, block_q, block_k, interpret, causal, res, g):
    q, k, v, mask, out, lse = res
    blocks = _blocks_viable(q.shape[1], k.shape[1], block_q, block_k)
    if lse is not None and blocks is not None:
        dq, dk, dv = _flash_backward(
            q, k, v, mask, out, lse, g, scale, *blocks, interpret, causal
        )
        return dq, dk, dv, None
    # Small-block fallback: the forward ran through XLA, so recompute
    # the XLA graph's exact gradients.
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(
            q, k, v, mask, scale=scale, causal=causal
        ),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in `attention_fn` backed by the Pallas flash kernels.

    Default blocks (512, 1024) are tuned on v5e: the (bq, bk) grid-step
    count — not matmul rate — capped throughput at the old (128, 128)
    (measured 7 -> 24 TF/s forward at T=8k, B=2, H=8, dh=64; shorter
    sequences shrink blocks to fit automatically).

    `interpret=None` auto-selects: compiled on TPU, interpreter
    elsewhere (tests). See module docstring for scope.

    Availability is probed ONCE at import (`_VMEM`, module top): on a
    build without `jax.experimental.pallas.tpu` the call degrades to
    the dense `dot_product_attention` reference instead of raising —
    the same probe-at-import / fall-back-at-call shape as
    `ops/quant_matmul.quant_matmul`, so a serving or training step
    composed against `flash_attention` stays runnable (slower, denser)
    on exotic builds rather than failing mid-request (ISSUE 16
    satellite; the old call-time RuntimeError turned a missing
    OPTIONAL dependency into a hard fault).
    """
    if _VMEM is None:
        return dot_product_attention(
            q, k, v, mask, scale=scale, causal=causal
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if mask is not None and mask.ndim != 2:
        raise NotImplementedError(
            "flash_attention supports (B, Tkv) key-validity masks; use "
            "dot_product_attention for general logit masks"
        )
    return _flash(q, k, v, mask, scale, block_q, block_k, interpret, causal)
