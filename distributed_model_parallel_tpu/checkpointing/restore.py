"""Resharding restore — any saved layout onto any current mesh.

`restore_checkpoint` is the unified entry the trainer calls: a
directory holding a sharded manifest restores through chunk reassembly;
anything else falls back to the legacy single-`.npz` reader
(`training/checkpoint.restore_checkpoint`) — same signature, same
return, so old checkpoints keep working unchanged.

Resharding is the point: each leaf is reassembled to its FULL host
array from whatever shard layout the manifest records (S=4 FSDP, TP
columns, a 2×2 dcn×ici hybrid ...) — the canonical form every engine
already restores through — and the engine's `from_canonical` /
`device_put(state, state_shardings)` re-slices it for the CURRENT mesh.
An S=4 checkpoint therefore loads onto S=8, S=2, or a hybrid mesh with
no format conversion step (Megatron SC'21's restore-time repartitioning
argument; PAPERS.md). Bit-exactness of the round trip is pinned in
tests/test_checkpoint_sharded.py.

Multi-process: same agreement protocol as the legacy reader — hosts
that see the files read them; hosts that don't build placeholders; all
agree on host-0's success before host-0's read is broadcast. The two
readers' broadcast sequences are IDENTICAL (ok flag, then the state
tuple), so hosts with per-host disks rendezvous even when only host 0
can see which format is on disk.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

from distributed_model_parallel_tpu.checkpointing.manifest import (
    Manifest,
    load_manifest,
    manifest_exists,
    manifest_path,
)


def _training_checkpoint():
    """Lazy import of the legacy reader: training/__init__ re-exports
    the Trainer, which imports THIS package — a module-level import
    here would close the cycle."""
    from distributed_model_parallel_tpu.training import checkpoint

    return checkpoint


def _template_shape_dtype(leaf):
    shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
    dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
    return shape, dtype


def _assemble_leaf(
    directory: str, manifest: Manifest, key: str, want_shape, want_dtype,
    npz_cache: dict, name: str = "ckpt",
) -> np.ndarray:
    rec = manifest.leaves.get(key)
    if rec is None:
        raise KeyError(
            f"sharded checkpoint at {manifest_path(directory, name)} is "
            f"missing leaf '{key}' — model structure changed since save"
        )
    if tuple(rec.shape) != tuple(want_shape):
        raise ValueError(
            f"checkpoint leaf '{key}' has shape {tuple(rec.shape)}, "
            f"expected {tuple(want_shape)}"
        )
    arr = np.empty(rec.shape, dtype=np.dtype(rec.dtype))
    for ch in rec.chunks:
        fname = manifest.shards[ch.file]
        if fname not in npz_cache:
            path = os.path.join(directory, fname)
            if not os.path.isfile(path):
                raise FileNotFoundError(
                    f"manifest references shard file {fname!r} which is "
                    f"absent from {directory} — a committed save never "
                    "leaves this state; was the directory partially "
                    "copied or hand-pruned?"
                )
            npz_cache[fname] = np.load(path)
        data = npz_cache[fname][ch.key]
        region = tuple(
            slice(s, s + n) for s, n in zip(ch.start, ch.shape)
        )
        arr[region] = data
    # NOT ascontiguousarray: this numpy promotes 0-d inputs to (1,)
    # there, and np.empty is contiguous already.
    return arr.astype(want_dtype, copy=False)


def _read_sharded(
    directory: str, name: str, leaves_with_paths
) -> Tuple[list, float, int]:
    manifest = load_manifest(directory, name)
    _path_str = _training_checkpoint()._path_str
    npz_cache: dict = {}
    try:
        new_leaves = []
        for path, leaf in leaves_with_paths:
            shape, dtype = _template_shape_dtype(leaf)
            new_leaves.append(_assemble_leaf(
                directory, manifest, _path_str(path), shape, dtype,
                npz_cache, name,
            ))
    finally:
        for f in npz_cache.values():
            f.close()
    return new_leaves, manifest.acc, manifest.epoch


def restore_checkpoint(
    directory: str,
    train_state_like: Any,
    *,
    name: str = "ckpt",
) -> Tuple[Any, float, int]:
    """Unified restore: sharded manifest when present, legacy `.npz`
    otherwise — `(state, best_acc, start_epoch)` either way, into the
    structure/shapes/dtypes of `train_state_like` (module docstring)."""
    if not manifest_exists(directory, name):
        return _training_checkpoint().restore_checkpoint(
            directory, train_state_like, name=name
        )
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        train_state_like
    )
    acc, epoch = 0.0, 0
    error: Optional[Exception] = None
    new_leaves = None
    try:
        new_leaves, acc, epoch = _read_sharded(
            directory, name, leaves_with_paths
        )
    except Exception as e:  # noqa: BLE001 — agreed + re-raised below
        error = e
    if new_leaves is None:
        new_leaves = [
            np.zeros(*_template_shape_dtype(leaf))
            for _, leaf in leaves_with_paths
        ]
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)

    if jax.process_count() > 1:
        # Same two-broadcast agreement as the legacy reader (module
        # docstring): non-zero-host failures fall to the placeholder
        # path and adopt host-0's read; host-0 failures surface on
        # every host together, never a one-sided raise into a hanging
        # broadcast.
        from jax.experimental import multihost_utils

        host0_failed = error is not None and jax.process_index() == 0
        ok = multihost_utils.broadcast_one_to_all(
            np.int32(0 if host0_failed else 1)
        )
        if not int(ok):
            raise error if error is not None else RuntimeError(
                "sharded checkpoint restore failed on host 0"
            )
        state, acc_ep = multihost_utils.broadcast_one_to_all(
            (state, (np.float32(acc), np.int32(epoch)))
        )
        acc, epoch = float(acc_ep[0]), int(acc_ep[1])
    elif error is not None:
        raise error
    return state, acc, epoch


def restore_subtree(
    directory: str,
    template: Any,
    *,
    name: str = "ckpt",
    prefix: str = "params",
) -> Tuple[Any, dict]:
    """Restore ONE subtree of a saved TrainState (e.g. just `params`
    for serving) from either format, plus the checkpoint's metadata
    dict (acc/epoch/extra — the serve CLI's model-config guard reads
    `extra`). `template` gives the subtree's structure; saved keys are
    looked up under `{prefix}/{leaf path}`."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        template
    )
    _path_str = _training_checkpoint()._path_str
    meta: dict = {}
    if manifest_exists(directory, name):
        manifest = load_manifest(directory, name)
        meta = {
            "acc": manifest.acc, "epoch": manifest.epoch,
            "format": "sharded", "mesh_axes": dict(manifest.mesh_axes),
        }
        if manifest.extra:
            meta.update(manifest.extra)
        npz_cache: dict = {}
        try:
            new_leaves = []
            for path, leaf in leaves_with_paths:
                shape, dtype = _template_shape_dtype(leaf)
                new_leaves.append(_assemble_leaf(
                    directory, manifest,
                    f"{prefix}/{_path_str(path)}", shape, dtype,
                    npz_cache, name,
                ))
        finally:
            for f in npz_cache.values():
                f.close()
    else:
        import json

        npz_path = os.path.join(directory, f"{name}.npz")
        if not os.path.isfile(npz_path):
            raise FileNotFoundError(
                f"Error: no checkpoint found at {npz_path} (nor a "
                f"{name}.manifest.json)"
            )
        with np.load(npz_path) as data:
            arrays = {k: data[k] for k in data.files}
        new_leaves = []
        for path, leaf in leaves_with_paths:
            key = f"{prefix}/{_path_str(path)}"
            if key not in arrays:
                raise KeyError(
                    f"checkpoint at {npz_path} is missing leaf "
                    f"'{key}' — model structure changed since save"
                )
            shape, dtype = _template_shape_dtype(leaf)
            arr = arrays[key]
            if tuple(arr.shape) != shape:
                raise ValueError(
                    f"checkpoint leaf '{key}' has shape "
                    f"{tuple(arr.shape)}, expected {shape}"
                )
            new_leaves.append(arr.astype(dtype))
        meta_path = os.path.join(directory, f"{name}.json")
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        meta["format"] = "legacy"
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta


def checkpoint_metadata(directory: str, name: str = "ckpt") -> dict:
    """acc / epoch / extra metadata of either checkpoint format WITHOUT
    touching array data — what `cli/serve.py --checkpoint` reads to
    fail fast on a model-config mismatch before building an engine.
    Raises FileNotFoundError when no checkpoint of either format is
    present."""
    import json

    if manifest_exists(directory, name):
        m = load_manifest(directory, name)
        meta = {
            "acc": m.acc, "epoch": m.epoch, "format": "sharded",
            "mesh_axes": dict(m.mesh_axes),
        }
        if m.extra:
            meta.update(m.extra)
        return meta
    npz_path = os.path.join(directory, f"{name}.npz")
    if not os.path.isfile(npz_path):
        raise FileNotFoundError(
            f"Error: no checkpoint found at {npz_path} (nor a "
            f"{name}.manifest.json)"
        )
    meta = {"format": "legacy"}
    meta_path = os.path.join(directory, f"{name}.json")
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta.update(json.load(f))
    return meta


def saved_topology(
    directory: str, name: str = "ckpt"
) -> Optional[dict]:
    """The mesh factorization a sharded checkpoint was taken at —
    `{"mesh_axes": {...}, "process_count": n, "epoch": e}` — or None
    for legacy/absent checkpoints (which record no topology). This is
    what `elastic_fit` hands to `make_trainer` so a restart may rebuild
    onto a RESIZED mesh and restore through the canonical form."""
    if not manifest_exists(directory, name):
        return None
    try:
        m = load_manifest(directory, name)
    except (OSError, ValueError, KeyError):
        return None
    return {
        "mesh_axes": dict(m.mesh_axes),
        "process_count": m.process_count,
        "epoch": m.epoch,
        "format": "sharded",
    }


__all__ = [
    "checkpoint_metadata",
    "restore_checkpoint",
    "restore_subtree",
    "saved_topology",
]
