"""Fail-fast + restart-from-checkpoint driver loop — with mesh resize.

The failure story SURVEY.md §5 plans (and the reference entirely lacks —
a crashed rank hangs its blocking `dist.send/recv` pipeline forever,
`distributed_layers.py:11-13,52`): training runs under a supervisor that
catches a failed attempt, rebuilds the trainer, resumes from the newest
checkpoint (`TrainerConfig.save_last` writes one per epoch), and retries
up to `max_restarts` times with capped exponential backoff. Failures
that exhaust the budget re-raise — fail-fast, never hang.

Genuine ELASTICITY (not just retry) rides the sharded checkpoint format
(`checkpointing/`): when `checkpoint_dir` is given, the supervisor reads
the restore manifest's saved mesh topology and hands it to
`make_trainer`, which may rebuild onto a RESIZED mesh — fewer hosts
after a preemption, more after a scale-up — and the resharding restore
path re-slices the canonical state for whatever mesh the new trainer
built. A `make_trainer` that accepts only `(resume)` keeps the old
retry-only contract unchanged.

On multi-host TPU deployments the inter-host failure *detection* is
`jax.distributed`'s own runtime (a lost host fails the collective with a
distributed-runtime error, which lands here as the caught exception);
this loop supplies the restart-from-checkpoint policy on top.
"""

from __future__ import annotations

import inspect
import time
from typing import Any, Callable, Optional, Sequence


def _wants_topology(make_trainer: Callable) -> bool:
    """True when `make_trainer` accepts a second positional parameter
    (the saved-topology dict) — the opt-in for mesh resize."""
    try:
        params = [
            p for p in inspect.signature(make_trainer).parameters.values()
            if p.kind in (
                p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD, p.VAR_POSITIONAL
            )
        ]
    except (TypeError, ValueError):  # builtins / odd callables
        return False
    if any(p.kind == p.VAR_POSITIONAL for p in params):
        return True
    return len(params) >= 2


def backoff_schedule(
    attempt: int,
    backoff_seconds: float,
    max_backoff_seconds: float,
) -> float:
    """Capped exponential: `backoff * 2**(attempt-1)`, clamped to
    `max_backoff_seconds` (attempt counts from 1). Pure so the schedule
    is testable without sleeping."""
    if attempt < 1:
        raise ValueError(f"attempt counts from 1, got {attempt}")
    return min(
        backoff_seconds * (2.0 ** (attempt - 1)), max_backoff_seconds
    )


def elastic_fit(
    make_trainer: Callable[..., Any],
    *,
    max_restarts: int = 2,
    backoff_seconds: float = 1.0,
    max_backoff_seconds: float = 60.0,
    jitter: Optional[Callable[[int], float]] = None,
    retry_on: Sequence[type] = (Exception,),
    on_restart: Optional[Callable[[int, BaseException], None]] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_name: str = "last",
) -> dict:
    """Run `make_trainer(resume).fit()` with restart-on-failure.

    `make_trainer(resume: bool)` must build a FRESH trainer; it receives
    resume=False on the first attempt and resume=True afterwards (its
    TrainerConfig should set `resume=resume and a checkpoint exists`, and
    `save_last=True` so restarts lose at most one epoch).

    Accepting a SECOND positional parameter opts into elasticity:
    `make_trainer(resume, topology)` receives the saved mesh
    factorization of the newest checkpoint under `checkpoint_dir`
    (`checkpointing.saved_topology` — a dict with 'mesh_axes',
    'process_count', 'epoch'; None on the first attempt, for legacy
    checkpoints, or when `checkpoint_dir` is not given) and may build
    its engine on a resized mesh; the sharded restore reshards the
    state to fit.

    Backoff before attempt k (k>=1) sleeps
    `min(backoff_seconds * 2**(k-1), max_backoff_seconds)` plus
    `jitter(k)` when a jitter hook is given (thundering-herd spread for
    fleet restarts). KeyboardInterrupt always propagates immediately.

    The returned summary (the final attempt's `fit()` dict) gains an
    `"elastic"` entry recording every restart's exception type and the
    backoff actually applied.
    """
    wants_topology = _wants_topology(make_trainer)
    restarts: list = []
    attempt = 0
    while True:
        topology = None
        if wants_topology and attempt > 0 and checkpoint_dir is not None:
            from distributed_model_parallel_tpu.checkpointing import (
                saved_topology,
            )

            topology = saved_topology(checkpoint_dir, checkpoint_name)
        if wants_topology:
            trainer = make_trainer(attempt > 0, topology)
        else:
            trainer = make_trainer(attempt > 0)
        try:
            result = trainer.fit()
            result["elastic"] = {
                "attempts": attempt + 1,
                "restarts": list(restarts),
            }
            return result
        except KeyboardInterrupt:
            raise
        except tuple(retry_on) as e:  # noqa: BLE001 — policy boundary
            attempt += 1
            if attempt > max_restarts:
                raise
            if on_restart is not None:
                on_restart(attempt, e)
            delay = backoff_schedule(
                attempt, backoff_seconds, max_backoff_seconds
            )
            if jitter is not None:
                delay += float(jitter(attempt))
            restarts.append({
                "attempt": attempt,
                "error_type": type(e).__name__,
                "error": str(e),
                "backoff_s": delay,
            })
            print(
                f"==> attempt {attempt}/{max_restarts} failed with "
                f"{type(e).__name__}: {e}; restarting from checkpoint "
                f"in {delay:.1f}s",
                flush=True,
            )
            time.sleep(delay)


__all__ = ["backoff_schedule", "elastic_fit"]
