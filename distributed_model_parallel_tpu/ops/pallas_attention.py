"""Fused flash-attention forward kernel in Pallas (TPU).

The attention hot op, tiled for the MXU with online softmax so the
(Tq, Tkv) logits matrix never materializes in HBM: the grid streams
(block_q x block_k) tiles, q@k^T runs on the MXU in f32, and the running
max / denominator / numerator live in VMEM scratch across the k-block
grid steps (TPU grids iterate the last axis innermost, and scratch
persists across steps — the canonical Pallas flash pattern).

Scope and honesty notes:
* Forward only. `flash_attention` carries a custom_vjp whose backward
  RECOMPUTES attention through the plain XLA path (`ops/attention.py`)
  — gradients are exact, but the backward pass materializes logits like
  the reference path does; a fused flash backward kernel is future work.
* Same contract as `dot_product_attention`: (B, T, H, Dh) tensors,
  optional (B, Tkv) key-validity mask, computes f32, returns q.dtype.
* Sequence lengths must divide the block sizes (the wrapper shrinks
  blocks to fit when the sequence is shorter); composes with ring /
  Ulysses sequence parallelism, which shard T across chips before any
  kernel runs.
* On non-TPU backends the kernel runs in Pallas interpret mode (slow,
  CI-only) so the numerics are testable on the 8-virtual-device mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - exotic builds
    pltpu = None
    _VMEM = None

from distributed_model_parallel_tpu.ops.attention import (
    dot_product_attention,
)

_NEG = jnp.finfo(jnp.float32).min


def _flash_step(q_ref, k_ref, v_ref, valid, o_ref,
                m_scr, l_scr, acc_scr, scale: float, nk: int,
                causal: bool = False):
    ki = pl.program_id(3)
    qi = pl.program_id(2)  # hoisted: program_id may not be called inside
    bq = q_ref.shape[2]    # the pl.when branch (no lowering rule there)
    bk = k_ref.shape[2]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr[:], _NEG)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    def compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # (bq, dh)
        k = k_ref[0, 0].astype(jnp.float32)              # (bk, dh)
        v = v_ref[0, 0].astype(jnp.float32)              # (bk, dh)

        s = jax.lax.dot_general(                         # (bq, bk) on MXU
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if valid is not None:  # static: masked kernel variant only
            s = jnp.where(valid[None, :], s, _NEG)
        if causal:  # global row >= global col within this tile pair
            rows = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0
            )
            cols = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1
            )
            s = jnp.where(rows >= cols, s, _NEG)

        m_prev = m_scr[:, 0]                             # (bq,)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        # exp(_NEG - m_new) underflows to 0 for any finite m_new; an
        # all-masked prefix keeps l == 0 and is guarded at finalize.
        p = jnp.exp(s - m_new[:, None])                  # (bq, bk)
        corr = jnp.exp(m_prev - m_new)                   # (bq,)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0] = m_new

    if causal:
        # Skip tiles strictly above the causal frontier: their logits
        # would all be _NEG and contribute nothing, but the MXU work and
        # K/V DMA are ~half the grid at long T — predicate them away.
        pl.when(ki * bk <= qi * bq + bq - 1)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, 0]
        denom = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_scr[:] / denom[:, None]).astype(o_ref.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, nk: int,
                  causal: bool):
    _flash_step(q_ref, k_ref, v_ref, mask_ref[0] != 0, o_ref,
                m_scr, l_scr, acc_scr, scale, nk, causal)


def _flash_kernel_nomask(q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale: float, nk: int,
                         causal: bool):
    # mask=None specialization: no dummy mask streamed per grid step, no
    # per-tile where on the hot path.
    _flash_step(q_ref, k_ref, v_ref, None, o_ref,
                m_scr, l_scr, acc_scr, scale, nk, causal)


def _pick_block(t: int, want: int) -> int:
    """Largest divisor of `t` that is <= want (block shapes must tile the
    sequence exactly)."""
    b = min(want, t)
    while t % b:
        b -= 1
    return b


def _flash_forward(q, k, v, mask, scale, block_q, block_k, interpret,
                   causal=False):
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    if bq < 8 or bk < 8:
        # Awkward sequence lengths (prime/odd) would force sub-sublane
        # blocks — a silent performance cliff and a Mosaic tiling risk.
        # The XLA path is the better program there.
        return dot_product_attention(
            q, k, v, mask, scale=scale, causal=causal
        )
    nq, nk = tq // bq, tk // bk

    # (B, H, T, Dh) layout for clean (seq, head_dim) blocks.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    qspec = pl.BlockSpec((1, 1, bq, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kspec = pl.BlockSpec((1, 1, bk, dh), lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    operands = [qt, kt, vt]
    in_specs = [qspec, kspec, kspec]
    if mask is not None:
        kernel = functools.partial(
            _flash_kernel, scale=scale, nk=nk, causal=causal
        )
        operands.append(mask.astype(jnp.int8))
        in_specs.append(
            pl.BlockSpec((1, bk), lambda bi, hi, qi, ki: (bi, ki))
        )
    else:
        kernel = functools.partial(
            _flash_kernel_nomask, scale=scale, nk=nk, causal=causal
        )
    out = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, bq, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, tq, dh), q.dtype),
        scratch_shapes=[
            _VMEM((bq, 1), jnp.float32),   # running max
            _VMEM((bq, 1), jnp.float32),   # running denominator
            _VMEM((bq, dh), jnp.float32),  # running numerator
        ],
        interpret=interpret,
    )(*operands)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, scale, block_q, block_k, interpret, causal):
    return _flash_forward(
        q, k, v, mask, scale, block_q, block_k, interpret, causal
    )


def _flash_fwd(q, k, v, mask, scale, block_q, block_k, interpret, causal):
    out = _flash_forward(
        q, k, v, mask, scale, block_q, block_k, interpret, causal
    )
    return out, (q, k, v, mask)


def _flash_bwd(scale, block_q, block_k, interpret, causal, res, g):
    # Exact gradients by recomputing attention through the XLA reference
    # path (see module docstring).
    q, k, v, mask = res
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(
            q, k, v, mask, scale=scale, causal=causal
        ),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in `attention_fn` backed by the Pallas flash forward kernel.

    `interpret=None` auto-selects: compiled on TPU, interpreter
    elsewhere (tests). See module docstring for scope.
    """
    if _VMEM is None:
        raise RuntimeError(
            "flash_attention needs jax.experimental.pallas.tpu, which "
            "failed to import in this environment; use "
            "ops.attention.dot_product_attention instead"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if mask is not None and mask.ndim != 2:
        raise NotImplementedError(
            "flash_attention supports (B, Tkv) key-validity masks; use "
            "dot_product_attention for general logit masks"
        )
    return _flash(q, k, v, mask, scale, block_q, block_k, interpret, causal)
