"""Fully-sharded data parallelism (ZeRO-3 style) over the `'data'` axis.

Absent from the reference (its DataParallel replicates every parameter
on every GPU — the memory ceiling ZeRO exists to remove); first-class
here. Like TP/EP, FSDP on TPU is a sharding POLICY, not a runtime: each
parameter tensor is sharded along its largest divisible dimension over
`'data'`, the optimizer state follows it (`state_shardings`), and the
XLA SPMD partitioner inserts what DeepSpeed/FairScale hand-build —
an all-gather of each weight right before its op (freed after use) and
a reduce-scatter of its gradient, overlapped with compute by the
scheduler. Per-device param+optimizer memory scales 1/N while the math
stays EXACTLY data parallelism (trajectory parity with plain DP is
pinned in tests/test_fsdp.py).

Tiny leaves (BN/LN scales, biases below `min_shard_elems`) stay
replicated: sharding them saves nothing and costs a collective each.

`grad_reduction="bucketed"` swaps the declarative jit step for an
EXPLICIT shard_map program — the bucketed-reduce-scatter twin of
`DDPEngine(grad_reduction="bucketed")`: parameters stay stored 1/N
(same `fsdp_specs` layout, checkpoints interoperate), each sharded
leaf is all-gathered on entry, and the gradient pytree is reduced
through the Reducer-style flat buckets of `ops/grad_reduction.py` —
per-bucket chunked-ppermute reduce-scatter over the intra-slice 'ici'
fabric, one cross-slice all-reduce on the 1/S shard over 'dcn', ring
all-gather back — after which every device slices ITS OWN 1/N shard of
each leaf locally and updates its parameter/moment shards in place.
The bucket all-gather half is shared with the DDP reducer (a flat 1/N
bucket shard cannot be re-dealt into per-dimension leaf shards without
an equal-volume redistribution, so reusing the overlapped ring costs
nothing extra); the at-rest memory story — params and moments 1/N —
is unchanged. BatchNorm runs in SyncBN mode (global batch statistics),
matching the declarative engine's semantics; parity at rtol 1e-5 is
pinned in tests/test_grad_reduction.py.

Compose with the other axes by SUBCLASSING and overriding
`param_specs` (e.g. rule-matched leaves keep their 'model'/'expert'
spec, everything else falls to the FSDP shape policy); the `rules`
field itself is rejected here because this engine's specs are
shape-driven and silently ignoring rules would break a user's
sharding plan without an error.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.models.layers import Context
from distributed_model_parallel_tpu.ops.grad_reduction import (
    bucketed_pmean,
    data_replica_index,
)
from distributed_model_parallel_tpu.parallel.data_parallel import (
    TrainState,
    _apply_input_transform,
    _cast_input,
    _metrics,
    aux_loss,
)
from distributed_model_parallel_tpu.parallel.tensor_parallel import (
    TensorParallelEngine,
)
from distributed_model_parallel_tpu.runtime.compat import shard_map
from distributed_model_parallel_tpu.runtime.mesh import (
    data_axis_names,
    data_axis_size,
    data_hierarchy_axes,
)
from distributed_model_parallel_tpu.training.metrics import cross_entropy


def fsdp_specs(
    params_aval,
    n_shards: int,
    *,
    min_shard_elems: int = 1024,
    axes: Sequence[str] | str = "data",
):
    """Shape-driven PartitionSpec pytree: each leaf sharded over the
    data axis/axes along its largest dimension divisible by `n_shards`;
    leaves smaller than `min_shard_elems` (or with no divisible dim)
    stay replicated. `axes` is the mesh spelling of the data-parallel
    world — 'data', or ('dcn', 'ici') on a hybrid mesh."""
    entry = tuple(axes) if not isinstance(axes, str) else axes

    def spec_of(leaf):
        shape = getattr(leaf, "shape", ())
        if not shape or math.prod(shape) < min_shard_elems:
            return P()
        dims = sorted(
            range(len(shape)), key=lambda d: shape[d], reverse=True
        )
        for d in dims:
            if shape[d] % n_shards == 0:
                parts = [None] * len(shape)
                parts[d] = entry
                return P(*parts)
        return P()

    return jax.tree_util.tree_map(spec_of, params_aval)


def _sharded_dim(spec: P):
    """(dim, axes) of the single sharded dimension in an fsdp spec, or
    (None, None) for replicated leaves."""
    for d, part in enumerate(spec):
        if part is not None:
            return d, part
    return None, None


@dataclasses.dataclass
class FSDPEngine(TensorParallelEngine):
    """GSPMD fully-sharded data parallelism: batch AND parameters (and
    optimizer moments, via `state_shardings`) sharded over the data
    axes. Same API as every other engine. `grad_reduction="bucketed"`
    selects the explicit bucketed-reduce-scatter step (module
    docstring)."""

    rules: tuple = ()  # shape-driven engine: rules are rejected, below
    # Leaves below this many elements stay replicated (BN scales etc.).
    min_shard_elems: int = 1024
    # "monolithic": declarative jit step, partitioner-inserted
    # gather/scatter (default). "bucketed": explicit shard_map step with
    # Reducer-style hierarchical flat-bucket gradient reduction.
    grad_reduction: str = "monolithic"
    bucket_mb: float = 25.0

    def __post_init__(self):
        if self.rules:
            raise ValueError(
                "FSDPEngine shards by shape policy, not path rules; "
                "passing rules here would be silently ignored. Subclass "
                "and override param_specs to compose FSDP with "
                "'model'/'expert' rule sharding."
            )
        if self.grad_reduction not in ("monolithic", "bucketed"):
            raise ValueError(
                "grad_reduction must be 'monolithic' or 'bucketed', "
                f"got {self.grad_reduction!r}"
            )
        if self.grad_reduction == "bucketed":
            if self.collective_matmul:
                # The explicit step below never threads a matmul policy
                # through Context — silently dropping the flag would
                # train without the requested rings (the monolithic
                # path at least fails on its missing 'model' axis).
                raise ValueError(
                    "collective_matmul=True is not supported by the "
                    "bucketed FSDP step (no matmul policy is threaded "
                    "through the explicit shard_map program)"
                )
            self._build_bucketed()
        else:
            super().__post_init__()

    def param_specs(self, p_aval):
        return fsdp_specs(
            p_aval, data_axis_size(self.mesh),
            min_shard_elems=self.min_shard_elems,
            axes=data_axis_names(self.mesh),
        )

    # ------------------------------------- explicit bucketed-RS step

    def _build_bucketed(self):
        """The shard_map twin of the declarative step: same state
        layout (`_state_sh`), explicit collectives — per-leaf weight
        all-gather on entry, bucketed hierarchical gradient reduction,
        local 1/N slice, sharded optimizer update."""
        mesh = self.mesh
        d_axes, ici_axis, dcn_axis = data_hierarchy_axes(mesh)
        n_data = data_axis_size(mesh)
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(d_axes))
        cdt = self.compute_dtype
        tf = self.input_transform
        model = self.model
        bucket_mb = self.bucket_mb

        key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
        p_aval, s_aval = jax.eval_shape(model.init, key_aval)
        pspecs = self.param_specs(p_aval)
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        param_sh = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), pspecs,
            is_leaf=is_spec,
        )
        self._state_sh = TrainState(
            param_sh,
            jax.tree_util.tree_map(lambda _: self._repl, s_aval),
            self.optimizer.state_shardings(param_sh, self._repl),
            self._repl,
        )
        # The same layout as P specs, for shard_map in/out_specs.
        state_specs = TrainState(
            pspecs,
            jax.tree_util.tree_map(lambda _: P(), s_aval),
            self.optimizer.state_shardings(pspecs, P()),
            P(),
        )

        def gather_params(params):
            """Per-leaf weight all-gather: the ZeRO-3 'materialize right
            before use' collective, explicit."""

            def gather(leaf, spec):
                d, axes = _sharded_dim(spec)
                if d is None:
                    return leaf
                return lax.all_gather(leaf, axes, axis=d, tiled=True)

            return jax.tree_util.tree_map(gather, params, pspecs)

        def shard_grads(grads):
            """Slice this device's 1/N of each fully-reduced leaf —
            local, no collective (the bucket rings already placed the
            reduced bytes everywhere)."""
            idx = data_replica_index(d_axes)

            def slice_leaf(leaf, spec):
                d, _ = _sharded_dim(spec)
                if d is None:
                    return leaf
                block = leaf.shape[d] // n_data
                return lax.dynamic_slice_in_dim(
                    leaf, idx * block, block, axis=d
                )

            return jax.tree_util.tree_map(slice_leaf, grads, pspecs)

        def shard_step(ts: TrainState, images, labels, lr):
            rng = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(0), ts.step),
                data_replica_index(d_axes),
            )
            images_c = _cast_input(
                _apply_input_transform(tf, images, ts.step, True), cdt
            )
            full_params = gather_params(ts.params)

            def loss_fn(params, model_state):
                # bn_axis: global batch statistics, matching the
                # declarative engine (plain jit = SyncBN semantics).
                logits, new_state = model.apply(
                    params, model_state, images_c,
                    Context(train=True, bn_axis=d_axes, rng=rng,
                            dtype=cdt),
                )
                ce = cross_entropy(logits, labels)
                return ce + aux_loss(new_state), (new_state, logits, ce)

            (_, (new_state, logits, ce)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(full_params, ts.model_state)
            grads = bucketed_pmean(
                grads, ici_axis, dcn_axis, bucket_mb=bucket_mb
            )
            params, opt_state = self.optimizer.update(
                ts.params, ts.opt_state, shard_grads(grads), lr
            )
            new_ts = TrainState(params, new_state, opt_state, ts.step + 1)
            m = _metrics(ce, logits, labels)
            m = jax.tree_util.tree_map(
                lambda v: lax.psum(v, d_axes), m
            )
            return new_ts, m

        def shard_eval(ts: TrainState, images, labels):
            images_c = _cast_input(
                _apply_input_transform(tf, images, ts.step, False), cdt
            )
            logits, _ = model.apply(
                gather_params(ts.params), ts.model_state, images_c,
                Context(train=False, dtype=cdt),
            )
            loss = cross_entropy(logits, labels)
            m = _metrics(loss, logits, labels)
            return jax.tree_util.tree_map(
                lambda v: lax.psum(v, d_axes), m
            )

        donate = (0,) if self.donate else ()
        self.train_step = jax.jit(
            shard_map(
                shard_step, mesh=mesh,
                in_specs=(state_specs, P(d_axes), P(d_axes), P()),
                out_specs=(state_specs, P()),
                check_vma=False,
            ),
            donate_argnums=donate,
        )
        self.eval_step = jax.jit(
            shard_map(
                shard_eval, mesh=mesh,
                in_specs=(state_specs, P(d_axes), P(d_axes)),
                out_specs=P(),
                check_vma=False,
            )
        )


__all__ = ["FSDPEngine", "fsdp_specs"]
