"""Metrics registry — counters, gauges, histograms with streaming
quantiles; Prometheus text exposition + JSON export.

`trace.py` answers "what did the host loops spend their time ON"
(a timeline); this module answers "what is the DISTRIBUTION of the
things they did" (step-time / fetch-stall / checkpoint-blocked
histograms in the Trainer, per-request queued/TTFT/per-token latency
histograms plus goodput and occupancy in the serving path, snapshot vs
background-write in the checkpoint writer). It is also the ONE home
for percentile math: the serving scheduler's latency report and
bench.py's p50/p99 columns both route through `exact_quantile`, so
there is exactly one interpolation rule in the tree (pinned equal to
`numpy.percentile`'s default linear rule on canned latencies).

Design constraints, same priority order as `trace.py`:

* **Zero-cost off-path.** The registry is DISABLED by default; a
  disabled call site pays one attribute load + one branch and
  allocates nothing — no instrument objects, no dict entries
  (`len(registry) == 0` stays true). Safe to leave permanently wired
  into hot host loops.
* **Thread-safe.** The checkpoint writer thread observes concurrently
  with the main loop; one lock around instrument creation and every
  mutation.
* **Deterministic under test.** No wall time anywhere: instruments
  record caller-supplied VALUES (callers take timestamps from
  `trace.get_tracer().now()`, the injectable clock), insertion order
  is preserved, and exports sort by name — canned values yield a
  byte-stable golden exposition file.

Histogram quantiles are hybrid exact/streaming: up to `exact_cap`
samples are kept verbatim and quantiles use the numpy-equal linear
interpolation; past the cap, samples fold into log-spaced buckets
(ratio ``GROWTH`` per bucket) and quantiles answer with the bucket's
geometric midpoint — relative error bounded by ``sqrt(GROWTH) - 1``
(~4.5%), the documented streaming bound.

This module also carries THE documented name registries
(`METRIC_NAMES`, `TRACE_EVENT_NAMES`): every metric/span/counter name
emitted anywhere in the package must appear here (the conftest
META-CHECK scans call sites with `scan_emitted_names` and fails
collection naming any stray), so the exposition surface can never
silently grow an undocumented series.

No jax, no numpy: importable everywhere, including the jax-free
analysis/report layers and the writer thread.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Dict, List, Optional, Tuple

# ------------------------------------------------- documented registry
#
# THE catalog of every series emitted in-tree. A new call site must add
# its name here (with a one-line meaning) or tier-1 collection fails
# naming the stray (conftest META-CHECK over `scan_emitted_names`).

#: Metric names (this registry's counters/gauges/histograms).
METRIC_NAMES: Dict[str, str] = {
    # Trainer epoch loop (training/trainer.py) — seconds histograms.
    "train_fetch_s": (
        "host input fetch per batch (group host-load time / batches "
        "in the group) — the data-stall distribution"
    ),
    "train_step_s": (
        "host loop time per batch at dispatch granularity (boundary "
        "to boundary, data fetch included; the progress print reads "
        "the PREVIOUS group's metrics so its readback fence never "
        "lands in these samples)"
    ),
    "train_checkpoint_blocked_s": (
        "how long one checkpoint save held the epoch loop (whole "
        "write for sync formats; device->host snapshot under "
        "async_save)"
    ),
    "train_batches_total": "batches dispatched (counter)",
    # Serving (serving/scheduler.py + engine.py).
    "serve_queued_s": "per request: submit -> admission",
    "serve_ttft_s": "per request: submit -> first token (TTFT)",
    "serve_token_s": "per generated token: decode-step latency",
    "serve_prefill_s": "per prefill call: host time incl. logit fetch",
    "serve_decode_step_s": "per engine decode step: host time",
    "serve_batch_occupancy": "active slots in the last decode step",
    "serve_goodput": (
        "occupied / total slot-steps over the finished set (set at "
        "report time)"
    ),
    "serve_tokens_total": "generated tokens (counter)",
    "serve_kv_pages_in_use": (
        "live KV pages in the paged pool after the last engine "
        "iteration (page-granular allocation scales with live tokens, "
        "not slots*max_len — serving/kv_cache.py)"
    ),
    "serve_prefix_hits_total": (
        "requests whose prompt reused >= 1 cached prefix page "
        "(prompt caching; counter)"
    ),
    # Speculative decoding (serving/speculative.py).
    "serve_spec_accept_len": (
        "per verify round per slot: tokens emitted (accepted draft "
        "prefix + the correction/bonus token, so 1..k+1) — the "
        "realized-speedup distribution"
    ),
    "serve_spec_tokens_total": (
        "tokens emitted by speculative verify rounds (counter; subset "
        "of serve_tokens_total)"
    ),
    # Checkpointing (checkpointing/save.py + writer.py).
    "ckpt_snapshot_s": "device->host snapshot half of a sharded save",
    "ckpt_background_write_s": "file-I/O half, on the writer thread",
}

#: Trace event names (trace.py span/counter/complete/instant sites).
TRACE_EVENT_NAMES: Dict[str, str] = {
    "fetch": "Trainer: host load + device placement of one group",
    "step": "Trainer: the dispatch call (enqueue under async dispatch)",
    "sync": "Trainer: value-fetch fences where device time surfaces",
    "checkpoint_blocked": "Trainer: a save holding the epoch loop",
    "ckpt_snapshot": "checkpointing: device->host snapshot (step path)",
    "ckpt_background_write": "checkpointing: writer-thread file I/O",
    "prefill": (
        "serving: one prompt ingest (engine span) / the admit->first-"
        "token request leg (scheduler track)"
    ),
    "decode_step": "serving: one mixed-position batch decode step",
    "prefill_chunk": (
        "serving: one chunked-prefill ingest (prefill_chunk tokens of "
        "one slot's prompt, sharing the iteration with decode)"
    ),
    "queued": "serving request leg: submit -> admission",
    "decode": "serving request leg: first token -> eviction",
    "batch_occupancy": "serving counter: active slots per decode step",
    "draft_round": (
        "serving: one speculative proposal round (k draft decode "
        "steps over the active set, serving/speculative.py)"
    ),
    "verify_step": (
        "serving: one speculative verify step (target scores k+1 "
        "positions per slot in one chunk-shaped iteration)"
    ),
}


# ----------------------------------------------------- quantile (ONE)


def exact_quantile(samples, q: float) -> Optional[float]:
    """The repo's one percentile rule: linear interpolation between
    closest ranks, bit-equal to ``numpy.percentile(xs, q)`` (default
    method) on the same samples. `q` in [0, 100]; None when empty."""
    xs = sorted(samples)
    n = len(xs)
    if n == 0:
        return None
    if n == 1:
        return float(xs[0])
    h = (n - 1) * (q / 100.0)
    lo = int(math.floor(h))
    if lo >= n - 1:
        return float(xs[-1])
    frac = h - lo
    return float(xs[lo]) + frac * (float(xs[lo + 1]) - float(xs[lo]))


# --------------------------------------------------------- instruments


class Counter:
    """Monotonic total (float). Mutated only through the registry."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Gauge:
    """Last-written value."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


#: Streaming-bucket growth ratio: quantile answers are the bucket's
#: geometric midpoint, so the relative error is <= sqrt(GROWTH) - 1.
GROWTH = 2.0 ** 0.125  # ~9.05% bucket width -> ~4.4% quantile bound
_LOG_GROWTH = math.log(GROWTH)
_BUCKET_BASE = 1e-9  # smallest resolvable positive value (seconds-ish)


class Histogram:
    """Hybrid exact/streaming histogram (module docstring). Values are
    unit-agnostic floats; negative values clamp into the zero bucket.
    Not thread-safe on its own — the registry serializes access."""

    __slots__ = ("count", "total", "vmin", "vmax", "exact_cap",
                 "_samples", "_buckets", "_zero")

    def __init__(self, exact_cap: int = 4096):
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.exact_cap = exact_cap
        self._samples: Optional[List[float]] = []
        self._buckets: Dict[int, int] = {}
        self._zero = 0  # values <= _BUCKET_BASE (incl. exact zeros)

    @property
    def streaming(self) -> bool:
        return self._samples is None

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        if self._samples is not None:
            self._samples.append(v)
            if len(self._samples) > self.exact_cap:
                for s in self._samples:
                    self._bucket(s)
                self._samples = None  # streaming from here on
            return
        self._bucket(v)

    def _bucket(self, v: float) -> None:
        if v <= _BUCKET_BASE:
            self._zero += 1
            return
        idx = int(math.floor(math.log(v / _BUCKET_BASE) / _LOG_GROWTH))
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def quantile(self, q: float) -> Optional[float]:
        """Exact (numpy-equal) below the cap; bucket geometric midpoint
        beyond it (relative error <= sqrt(GROWTH) - 1)."""
        if self.count == 0:
            return None
        if self._samples is not None:
            return exact_quantile(self._samples, q)
        # Nearest-rank walk over the sorted sparse buckets.
        rank = max(0, min(self.count - 1, math.ceil(q / 100.0 * self.count) - 1))
        seen = self._zero
        if rank < seen:
            return 0.0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if rank < seen:
                lo = _BUCKET_BASE * GROWTH ** idx
                return lo * math.sqrt(GROWTH)
        return self.vmax  # numerical belt-and-braces

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "sum": round(self.total, 9),
            "min": round(self.vmin, 9) if self.count else None,
            "max": round(self.vmax, 9) if self.count else None,
            "mode": "streaming" if self.streaming else "exact",
        }
        out["quantiles"] = {
            f"p{q:g}": (
                round(self.quantile(q), 9)
                if self.count else None
            )
            for q in (50, 90, 99)
        }
        return out


# ------------------------------------------------------------ registry


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers without a trailing .0,
    floats via repr (deterministic shortest round-trip)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class MetricsRegistry:
    """Counters, gauges, histograms behind ONE enabled flag (module
    docstring). All mutators are thread-safe and early-return on the
    disabled path without allocating anything."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    # ------------------------------------------------------- mutators

    def inc(self, name: str, value: float = 1.0) -> None:
        """Add to a monotonic counter (one branch when disabled)."""
        if not self.enabled:
            return
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            c.value += float(value)

    def gauge(self, name: str, value: float) -> None:
        """Set a gauge to its latest value."""
        if not self.enabled:
            return
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            g.value = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one histogram sample."""
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    # -------------------------------------------------------- readers

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def __len__(self) -> int:
        with self._lock:
            return (
                len(self._counters) + len(self._gauges)
                + len(self._hists)
            )

    # -------------------------------------------------------- exports

    def to_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4): counters and gauges as
        single samples, histograms as summaries (p50/p90/p99 quantile
        samples + _sum/_count). Sorted by name; byte-stable for canned
        values."""
        lines: List[str] = []
        # The WHOLE render happens under the lock: quantile() walks
        # histogram internals that a concurrent observe() (e.g. the
        # checkpoint writer thread) may be re-bucketing mid-call —
        # same discipline as to_json's locked snapshot().
        with self._lock:
            for name, c in sorted(self._counters.items()):
                lines.append(
                    f"# HELP {name} {METRIC_NAMES.get(name, '')}"
                )
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(c.value)}")
            for name, g in sorted(self._gauges.items()):
                lines.append(
                    f"# HELP {name} {METRIC_NAMES.get(name, '')}"
                )
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(g.value)}")
            for name, h in sorted(self._hists.items()):
                lines.append(
                    f"# HELP {name} {METRIC_NAMES.get(name, '')}"
                )
                lines.append(f"# TYPE {name} summary")
                for q in (50, 90, 99):
                    v = h.quantile(q)
                    lines.append(
                        f'{name}{{quantile="{q / 100}"}} '
                        f"{_fmt(round(v, 9)) if v is not None else 'NaN'}"
                    )
                lines.append(f"{name}_sum {_fmt(round(h.total, 9))}")
                lines.append(f"{name}_count {h.count}")
        return "\n".join(lines) + "\n"

    def to_json(self) -> dict:
        """The machine twin of the exposition — what `--metrics-out`
        writes and `tools/obsreport --metrics` ingests."""
        with self._lock:
            return {
                "counters": {
                    k: round(v.value, 9)
                    for k, v in sorted(self._counters.items())
                },
                "gauges": {
                    k: round(v.value, 9)
                    for k, v in sorted(self._gauges.items())
                },
                "histograms": {
                    k: h.snapshot()
                    for k, h in sorted(self._hists.items())
                },
            }

    def export(self, path: str) -> str:
        """Write the export to `path`: Prometheus text when it ends in
        `.prom`, JSON otherwise. Returns the path."""
        if path.endswith(".prom"):
            payload = self.to_prometheus()
        else:
            payload = json.dumps(self.to_json(), indent=1) + "\n"
        with open(path, "w") as f:
            f.write(payload)
        return path

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


# ---------------------------------------------------- global registry

_ENV_FLAG = "DMP_METRICS"
_global_metrics: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def _env_enabled() -> bool:
    v = os.environ.get(_ENV_FLAG, "").strip().lower()
    return v not in ("", "0", "false", "off")


def get_metrics() -> MetricsRegistry:
    """The process-wide registry every wired layer records to. Created
    on first use; starts enabled iff DMP_METRICS is set."""
    global _global_metrics
    m = _global_metrics
    if m is None:
        with _global_lock:
            m = _global_metrics
            if m is None:
                m = MetricsRegistry(enabled=_env_enabled())
                _global_metrics = m
    return m


def set_metrics(registry: Optional[MetricsRegistry]) -> None:
    """Swap the process-wide registry (tests inject a fresh instance;
    None resets to the lazy default)."""
    global _global_metrics
    with _global_lock:
        _global_metrics = registry


def enable() -> MetricsRegistry:
    m = get_metrics()
    m.enabled = True
    return m


def disable() -> None:
    get_metrics().enabled = False


# ----------------------------------------------- emitted-name scanner

import re  # noqa: E402  (kept with its sole consumer)

#: call-site patterns -> which documented registry the name must be in.
_EMIT_PATTERNS: Tuple[Tuple[str, str], ...] = (
    (r"\.span\(\s*[\"']([A-Za-z0-9_]+)[\"']", "trace"),
    (r"\.counter\(\s*[\"']([A-Za-z0-9_]+)[\"']", "trace"),
    (r"\.instant\(\s*[\"']([A-Za-z0-9_]+)[\"']", "trace"),
    (r"\.complete\(\s*[\"']([A-Za-z0-9_]+)[\"']", "trace"),
    (r"\.observe\(\s*[\"']([A-Za-z0-9_]+)[\"']", "metric"),
    (r"\.inc\(\s*[\"']([A-Za-z0-9_]+)[\"']", "metric"),
    (r"\.gauge\(\s*[\"']([A-Za-z0-9_]+)[\"']", "metric"),
)


def scan_emitted_names(root: Optional[str] = None) -> Dict[str, List[str]]:
    """Walk the package source for span/counter/metric emission sites
    with a literal name and return {undocumented name: [file:line,
    ...]} — empty when every emitted name is in METRIC_NAMES /
    TRACE_EVENT_NAMES. The conftest META-CHECK fails collection on a
    non-empty answer, naming the stray."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    patterns = [(re.compile(p), kind) for p, kind in _EMIT_PATTERNS]
    strays: Dict[str, List[str]] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path) as f:
                    src = f.read()
            except OSError:
                continue
            for pat, kind in patterns:
                for m in pat.finditer(src):
                    name = m.group(1)
                    documented = (
                        TRACE_EVENT_NAMES if kind == "trace"
                        else METRIC_NAMES
                    )
                    if name in documented:
                        continue
                    line = src.count("\n", 0, m.start()) + 1
                    strays.setdefault(name, []).append(
                        f"{os.path.relpath(path, root)}:{line}"
                    )
    return strays


__all__ = [
    "Counter",
    "Gauge",
    "GROWTH",
    "Histogram",
    "METRIC_NAMES",
    "MetricsRegistry",
    "TRACE_EVENT_NAMES",
    "disable",
    "enable",
    "exact_quantile",
    "get_metrics",
    "scan_emitted_names",
    "set_metrics",
]
