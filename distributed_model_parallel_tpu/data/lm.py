"""Language-model data: deterministic synthetic corpus + window loader.

The reference has no text path at all; this module is the LM twin of
`data/datasets.py`'s `synthetic`: a corpus CI can regenerate bit-for-bit
with no downloads (this sandbox has zero egress), whose statistics make
convergence measurable — tokens follow a fixed random first-order Markov
chain, so the achievable cross-entropy floor is the chain's conditional
entropy (reported by `chain_entropy`) and a model that learns the
transition table shows a clear loss drop toward it.
"""

from __future__ import annotations

import numpy as np


def _chain_tables(rng: np.random.RandomState, vocab_size: int,
                  branching: int):
    """The chain's (successor-ids, probs) tables, drawn from `rng` —
    the SINGLE place the chain's RNG consumption order lives, shared by
    `synthetic_corpus` and `chain_entropy` so they can never describe
    two different chains."""
    live = vocab_size - 1  # ids 1..vocab_size-1
    succ = rng.randint(0, live, size=(live, branching))
    probs = rng.dirichlet(np.ones(branching), size=live)
    return succ, probs


def _walk(succ, probs, walk_rng, num_tokens: int) -> np.ndarray:
    out = np.empty(num_tokens, np.int32)
    state = walk_rng.randint(0, succ.shape[0])
    branching = succ.shape[1]
    for i in range(num_tokens):
        out[i] = state + 1
        state = succ[state, walk_rng.choice(branching, p=probs[state])]
    return out


def synthetic_corpus(
    vocab_size: int = 256,
    num_tokens: int = 1 << 17,
    seed: int = 0,
    branching: int = 4,
    stream_seed: int | None = None,
) -> np.ndarray:
    """A (num_tokens,) int32 token stream from a fixed random Markov
    chain: each token has `branching` possible successors with a fixed
    random distribution. Token id 0 is reserved (never emitted) so it
    can serve as padding downstream.

    `seed` fixes the CHAIN (transition table); `stream_seed` (default:
    same as seed) fixes the sampled path through it — a val split is the
    SAME chain walked with a different stream_seed, so train and val
    measure one task."""
    rng = np.random.RandomState(seed)
    succ, probs = _chain_tables(rng, vocab_size, branching)
    walk = (
        rng if stream_seed is None else np.random.RandomState(stream_seed)
    )
    return _walk(succ, probs, walk, num_tokens)


def chain_entropy(
    vocab_size: int = 256, seed: int = 0, branching: int = 4,
    num_sample_tokens: int = 1 << 15,
) -> float:
    """Entropy RATE (nats/token) of `synthetic_corpus`'s chain with the
    same parameters — the cross-entropy floor a perfect next-token model
    reaches on the stream.

    Weighted by the EMPIRICAL state-visit distribution of a sample walk
    (fixed internal seed), not a uniform average over states: the random
    chain is generally not uniform-stationary and may be reducible, so
    uniform weighting can sit above or below the floor the stream
    actually exhibits."""
    rng = np.random.RandomState(seed)
    succ, probs = _chain_tables(rng, vocab_size, branching)
    live = succ.shape[0]
    ent = np.zeros(live)
    for s in range(live):
        # merge duplicate successors before the entropy sum
        p = {}
        for j in range(branching):
            p[succ[s, j]] = p.get(succ[s, j], 0.0) + probs[s, j]
        ent[s] = -sum(v * np.log(v) for v in p.values() if v > 0)
    visits = np.bincount(
        _walk(succ, probs, np.random.RandomState(0xC0FFEE),
              num_sample_tokens) - 1,
        minlength=live,
    ).astype(np.float64)
    return float(ent @ (visits / visits.sum()))


class LMLoader:
    """Batches of contiguous (batch, seq_len) windows from a token
    stream, reshuffled per epoch (seeded — deterministic like the image
    Loader). Yields (ids, ids): the second element fills the engines'
    uniform (inputs, labels) slot; the causal-LM engines derive their
    shifted targets themselves (`gpt.lm_targets`)."""

    def __init__(self, corpus: np.ndarray, batch_size: int, seq_len: int,
                 *, shuffle: bool = True, seed: int = 0):
        self.corpus = np.asarray(corpus, np.int32)
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self.n_windows = len(self.corpus) // seq_len
        if self.n_windows < batch_size:
            raise ValueError(
                f"corpus has {self.n_windows} windows of {seq_len} tokens "
                f"but batch_size is {batch_size}"
            )

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        return self.n_windows // self.batch_size

    def __iter__(self):
        order = np.arange(self.n_windows)
        if self.shuffle:
            np.random.RandomState(self.seed + self._epoch).shuffle(order)
        for b in range(len(self)):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            ids = np.stack([
                self.corpus[i * self.seq_len:(i + 1) * self.seq_len]
                for i in idx
            ])
            yield ids, ids
