"""Paged KV cache + chunked prefill + prefix caching pins (ISSUE 15,
`serving/kv_cache.py` / `serving/decode.py` / `serving/engine.py`).

The load-bearing pins:

* **Logit parity** — the paged decode step is LOGIT-IDENTICAL (rtol
  1e-5) to dense full recompute for the replicated/TP/SP layouts, on
  ragged batches whose sequences straddle >= 3 pages, including a
  recycled slot mid-run. Paging is a storage change, never a math
  change.
* **Memory structure** — allocated pages for a ragged batch track live
  tokens: <= ceil(tokens/page) + one partial page per live sequence,
  and strictly under the contiguous layout's slots*max_len stripes
  (the PagedAttention waste claim, asserted from the pool
  bookkeeping).
* **Chunked prefill trajectory** — a chunk-ingested prompt produces
  byte-identical greedy tokens to the monolithic prefill and the
  contiguous engine.
* **Prefix caching** — a repeated prompt HITS (pages shared, prefill
  skipped), a divergent prompt resumes ingestion at the first
  unmatched page, and a write into a shared page copies first
  (copy-on-write), with the original sequence unperturbed.

S=4 sweeps are `slow` (tier-1 budget) with named tier-1 twins, per the
budget-rebalance convention.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models.gpt import GPTConfig, gpt_lm
from distributed_model_parallel_tpu.models.layers import Context
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.serving.engine import ServingEngine
from distributed_model_parallel_tpu.serving.kv_cache import (
    PagedKVCacheSpec,
    PagePool,
    PrefixCache,
    SlotAllocator,
)
from distributed_model_parallel_tpu.serving.sampling import (
    SamplingConfig,
    SlotSampler,
)
from distributed_model_parallel_tpu.serving.scheduler import Request

CFG = GPTConfig(
    vocab_size=61, dim=16, num_layers=2, num_heads=4, ffn_dim=32,
    max_position=16, dropout_rate=0.0,
)
# Ragged on purpose; with page_size=4 the 5-token prompt's decode walk
# crosses into its third page by step 4 (position 8).
PROMPT_LENS = (3, 5, 2)


@pytest.fixture(scope="module")
def dense():
    """Shared dense twin: params + a full-recompute next-token oracle."""
    model = gpt_lm(CFG)
    params, state = model.init(jax.random.PRNGKey(0))

    def next_logits(ids):
        ids = jnp.asarray(np.asarray(ids, np.int32))[None]
        logits, _ = model.apply(params, state, ids, Context(train=False))
        return np.asarray(logits[0, -1])

    return params, next_logits


def _prompts(seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randint(1, CFG.vocab_size, size=n).astype(np.int32)
        for n in PROMPT_LENS
    ]


def _greedy(next_logits, prompt, n):
    ids = list(prompt)
    out = []
    for _ in range(n):
        tok = int(next_logits(ids).argmax())
        out.append(tok)
        ids.append(tok)
    return out


def _assert_paged_decode_parity(eng, dense, *, steps=6, rtol=1e-5):
    """Monolithic-paged prefill of a ragged batch, `steps` decode
    tokens (sequences straddle >= 3 pages at page_size=4), then a
    RECYCLED slot (pages returned to the pool, fresh prompt lands on a
    recycled page set) — every emitted logit row vs dense full
    recompute."""
    params, next_logits = dense
    params = eng.place_params(params)
    prompts = _prompts()[: min(eng.num_slots, 3)]
    host = eng.new_host()
    cache = eng.init_cache()
    tokens = np.zeros((eng.num_slots,), np.int32)
    positions = np.zeros((eng.num_slots,), np.int32)
    active = np.zeros((eng.num_slots,), bool)
    seqs = {}

    def ingest(slot, prompt):
        nonlocal cache
        host.ensure_pages(slot, int(prompt.size))
        ids, length = eng.pad_prompt(prompt)
        cache, nl = eng.prefill(
            params, cache, host.device_table()[slot], ids, length
        )
        np.testing.assert_allclose(
            np.asarray(nl), next_logits(prompt), rtol=rtol, atol=1e-6
        )
        tok = int(np.asarray(nl).argmax())
        seqs[slot] = list(prompt) + [tok]
        tokens[slot] = tok
        positions[slot] = prompt.size
        active[slot] = True

    def step_all(n):
        nonlocal cache
        for _ in range(n):
            for slot in np.nonzero(active)[0]:
                cache = host.ensure_writable(
                    cache, int(slot), int(positions[slot])
                )
            cache, logits = eng.decode_step(
                params, cache, host.device_table(),
                jnp.asarray(positions), jnp.asarray(tokens),
                jnp.asarray(active),
            )
            logits = np.asarray(logits)
            for slot in seqs:
                np.testing.assert_allclose(
                    logits[slot], next_logits(seqs[slot]),
                    rtol=rtol, atol=1e-6,
                )
                tok = int(logits[slot].argmax())
                seqs[slot].append(tok)
                tokens[slot] = tok
                positions[slot] += 1

    for slot, prompt in enumerate(prompts):
        ingest(slot, prompt)
    step_all(steps)
    # The 5-token prompt has decoded to position 5+6=11: pages 0..2 of
    # page_size 4 — the >= 3-page straddle the acceptance pin names.
    assert int(positions[1]) // eng.paged_spec.page_size >= 2
    # Recycle slot 0: its PAGES return to the pool; a fresh prompt
    # re-allocates (possibly the same page ids, content overwritten up
    # to its own length) while the other slots decode on.
    before = host.pool.pages_in_use
    host.release(0)
    assert host.pool.pages_in_use < before
    positions[0] = 0
    del seqs[0]
    ingest(0, _prompts(seed=9)[2])
    step_all(2)


# ------------------------------------------------------------- layouts


def test_paged_decode_matches_dense_replicated(dense):
    eng = ServingEngine(
        CFG, num_slots=4, max_len=16, prefill_len=8, page_size=4
    )
    _assert_paged_decode_parity(eng, dense)


@pytest.mark.slow
def test_paged_decode_matches_dense_page2(dense):
    """page_size=2: a 5-token prompt spans 3 pages at PREFILL time
    already, and decode crosses a page boundary every other step.
    `slow` (tier-1 budget); tier-1 twin:
    test_paged_decode_matches_dense_replicated (page_size=4, same
    gather/write/scatter path with >= 3-page straddles by step 4)."""
    eng = ServingEngine(
        CFG, num_slots=4, max_len=16, prefill_len=8, page_size=2
    )
    _assert_paged_decode_parity(eng, dense)


@pytest.mark.parametrize("s", [
    2, pytest.param(4, marks=pytest.mark.slow),
])
def test_paged_decode_matches_dense_tp(s, dense, devices):
    """TP paged: pool heads-sharded over 'model', block-table gathers
    local per shard. S=4 is `slow`; its tier-1 twin is the S=2 case on
    the same code path."""
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="tp", num_slots=4, max_len=16, prefill_len=8,
        page_size=4,
    )
    _assert_paged_decode_parity(eng, dense)


@pytest.mark.parametrize("s", [
    2, pytest.param(4, marks=pytest.mark.slow),
])
def test_paged_decode_matches_dense_tp_collective_matmul(
    s, dense, devices,
):
    """Opted-in decode rings over the PAGED cache: the ring projections
    and the block-table gathers compose without touching each other's
    math (the HLO side — identical 4L(S-1) tagged permute chain — is
    the serve/S2/pg8/cm hlolint combo). S=4 is `slow`; tier-1 twin:
    the S=2 case."""
    mesh = make_mesh(MeshSpec(data=1, model=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="tp", num_slots=4, max_len=16, prefill_len=8,
        page_size=4, collective_matmul=True,
    )
    _assert_paged_decode_parity(eng, dense)


@pytest.mark.parametrize("s", [
    2, pytest.param(4, marks=pytest.mark.slow),
])
def test_paged_decode_matches_dense_sp(s, dense, devices):
    """SP paged: each shard owns a contiguous slice of EVERY page's
    positions; the per-shard partial attentions merge via the exact
    online-softmax recurrence. S=4 is `slow`; tier-1 twin: the S=2
    case."""
    mesh = make_mesh(MeshSpec(data=1, seq=s), devices=devices[:s])
    eng = ServingEngine(
        CFG, mesh, layout="sp", num_slots=4, max_len=16, prefill_len=8,
        page_size=4,
    )
    _assert_paged_decode_parity(eng, dense)


# ------------------------------------------- chunked prefill + pooling


def test_chunked_prefill_matches_monolithic_and_contiguous(dense):
    """The chunked-prefill trajectory pin: greedy tokens from the
    chunk-ingested paged engine == monolithic paged == the contiguous
    engine == dense greedy, under admission pressure (5 requests over
    2 slots, slot recycling, a prompt that is not chunk-aligned)."""
    params, next_logits = dense
    prompts = _prompts() + _prompts(seed=3)[:2]
    reqs = [
        Request(rid=i, prompt=p, max_new_tokens=4)
        for i, p in enumerate(prompts)
    ]
    runs = {}
    # (Non-chunk-aligned ingestion is pinned at LOGIT level by
    # test_unaligned_chunk_ingest_logit_parity — a fourth engine here
    # would re-cover it at trajectory level for another compile's
    # worth of tier-1 budget.)
    for key, kw in (
        ("contiguous", {}),
        ("paged", {"page_size": 4}),
        ("chunked", {"page_size": 4, "prefill_chunk": 4}),
    ):
        eng = ServingEngine(
            CFG, num_slots=2, max_len=16, prefill_len=8, **kw
        )
        sched = eng.run(eng.place_params(params), list(reqs))
        assert len(sched.finished) == len(reqs)
        runs[key] = {
            f.rid: f.tokens for f in sched.finished
        }
    expect = {
        i: _greedy(next_logits, p, 4) for i, p in enumerate(prompts)
    }
    for key, toks in runs.items():
        assert toks == expect, f"{key} diverged from dense greedy"


def test_unaligned_chunk_ingest_logit_parity(dense):
    """LOGIT-level pin for chunks that straddle page boundaries
    (prefill_chunk=3 over page_size=4: every chunk after the first
    starts mid-page, so the scatter-back must cover
    (chunk-1)//page + 2 pages — an undercount silently zeroes K/V at
    the straddled position, which a token-trajectory check can miss
    when magnitudes are tiny; regression for exactly that bug)."""
    params, next_logits = dense
    eng = ServingEngine(
        CFG, num_slots=2, max_len=16, prefill_len=8, page_size=4,
        prefill_chunk=3,
    )
    placed = eng.place_params(params)
    host = eng.new_host()
    cache = eng.init_cache()
    prompt = _prompts()[1]  # 5 tokens: chunks [0,3) + [3,5) span pages
    host.ensure_pages(0, int(prompt.size))
    start = 0
    while start < prompt.size:
        n = min(3, int(prompt.size) - start)
        ids = np.zeros((1, 3), np.int32)
        ids[0, :n] = prompt[start:start + n]
        cache, nl = eng.chunk_prefill(
            placed, cache, host.device_table()[0], jnp.asarray(ids),
            jnp.int32(start), jnp.int32(n),
        )
        start += n
    np.testing.assert_allclose(
        np.asarray(nl), next_logits(prompt), rtol=1e-5, atol=1e-6
    )
    # Decode reads the POOL (not the chunk step's view): a dropped
    # scatter page would surface here as wrong logits.
    seq = list(prompt) + [int(np.asarray(nl).argmax())]
    tokens = np.zeros((2,), np.int32)
    tokens[0] = seq[-1]
    positions = np.array([prompt.size, 0], np.int32)
    active = np.array([True, False])
    for _ in range(3):
        cache = host.ensure_writable(cache, 0, int(positions[0]))
        cache, logits = eng.decode_step(
            placed, cache, host.device_table(),
            jnp.asarray(positions), jnp.asarray(tokens),
            jnp.asarray(active),
        )
        np.testing.assert_allclose(
            np.asarray(logits)[0], next_logits(seq),
            rtol=1e-5, atol=1e-6,
        )
        seq.append(int(np.asarray(logits)[0].argmax()))
        tokens[0] = seq[-1]
        positions[0] += 1


@pytest.mark.slow
def test_chunked_lifts_prefill_len_cap(dense):
    """Chunked ingestion walks the prompt in place, so a prompt longer
    than the monolithic prefill_len pad serves fine (up to
    max_len - 1). `slow` (tier-1 budget); tier-1 twins:
    test_chunked_prefill_matches_monolithic_and_contiguous (the
    chunked run loop) and test_paged_spec_and_engine_guards (the
    cap/guard surface); the >prefill_len admission path also runs in
    the serving_admission bench leg."""
    params, next_logits = dense
    long_prompt = np.random.RandomState(5).randint(
        1, CFG.vocab_size, size=12
    ).astype(np.int32)
    eng = ServingEngine(
        CFG, num_slots=2, max_len=16, prefill_len=8, page_size=4,
        prefill_chunk=4,
    )
    sched = eng.run(eng.place_params(params), [
        Request(rid=0, prompt=long_prompt, max_new_tokens=3),
    ])
    assert sched.finished[0].tokens == _greedy(
        next_logits, long_prompt, 3
    )
    # The monolithic paged engine still enforces the pad cap.
    eng2 = ServingEngine(
        CFG, num_slots=2, max_len=16, prefill_len=8, page_size=4
    )
    with pytest.raises(ValueError, match="prefill_len"):
        eng2.run(eng2.place_params(params), [
            Request(rid=0, prompt=long_prompt, max_new_tokens=3),
        ])


def test_paged_memory_scales_with_live_tokens(dense):
    """The structural memory pin (acceptance criterion): after a
    ragged batch prefills, allocated pages == sum(ceil(len_i/page))
    <= ceil(total/page) + one partial page per live sequence, and the
    paged bytes sit strictly under the contiguous layout's
    slots*max_len stripes. Eviction returns PAGES (the recycled-slot
    half of the claim)."""
    params, _ = dense
    page = 4
    eng = ServingEngine(
        CFG, num_slots=4, max_len=16, prefill_len=8, page_size=page
    )
    placed = eng.place_params(params)
    host = eng.new_host()
    cache = eng.init_cache()
    prompts = _prompts()
    for slot, prompt in enumerate(prompts):
        host.ensure_pages(slot, int(prompt.size))
        ids, length = eng.pad_prompt(prompt)
        cache, _nl = eng.prefill(
            placed, cache, host.device_table()[slot], ids, length
        )
    lens = [int(p.size) for p in prompts]
    expect_pages = sum(-(-n // page) for n in lens)
    assert host.pool.pages_in_use == expect_pages
    total = sum(lens)
    assert expect_pages <= -(-total // page) + len(lens)  # +slack
    spec = eng.paged_spec
    contiguous_bytes = eng.num_slots * eng._slot_stripe_bytes
    assert host.pool.kv_cache_bytes == expect_pages * spec.page_bytes
    assert host.pool.kv_cache_bytes < contiguous_bytes
    # The SlotAllocator seam reports the contiguous layout's charge:
    # a max_len stripe per LIVE slot, position-independent.
    alloc = SlotAllocator(4, bytes_per_slot=eng._slot_stripe_bytes)
    for _ in prompts:
        alloc.alloc()
    assert alloc.kv_cache_bytes == 3 * eng._slot_stripe_bytes
    assert host.pool.kv_cache_bytes < alloc.kv_cache_bytes
    # Eviction returns pages, not a stripe.
    host.release(1)  # the 5-token slot: 2 pages
    assert host.pool.pages_in_use == expect_pages - 2


def test_undersized_pool_defers_admission_and_completes(dense):
    """Admission reserves each sequence's WHOLE page budget (prompt +
    max_new_tokens), so a pool too small for two concurrent sequences
    serves them one after the other — deferred, never crashed mid-run
    — and every greedy token still matches dense recompute. The
    exhaustion message itself is pinned at the PagePool level
    (test_page_pool_refcounts_and_reuse)."""
    params, next_logits = dense
    eng = ServingEngine(
        CFG, num_slots=2, max_len=16, prefill_len=8, page_size=4,
        num_pages=4, prefill_chunk=4,  # one 5+8-token sequence's worth
    )
    reqs = [
        Request(rid=i, prompt=_prompts()[1], max_new_tokens=8)
        for i in range(2)
    ]
    sched = eng.run(eng.place_params(params), reqs)
    assert len(sched.finished) == 2
    expect = _greedy(next_logits, _prompts()[1], 8)
    assert all(f.tokens == expect for f in sched.finished)
    rep = sched.latency_report()
    # The two sequences never overlapped: peak allocation is one
    # sequence's pages, bounded by the tiny pool.
    assert rep["paged"]["pages_in_use_peak"] <= 4
    # Only one slot was ever decode-active at a time.
    assert rep["mean_batch_occupancy"] == 1.0


# ------------------------------------------------------- prefix cache


def test_prefix_cache_hit_miss_cow(dense):
    """Hit / miss / copy-on-write in one trace: request A (miss)
    ingests and registers; B (identical prompt) skips its prefill via
    the full hit and COW-copies the shared partial page before its
    first write; C (shares only the first page) resumes ingestion at
    the divergent page. All three match dense greedy — sharing never
    perturbs anyone's logits."""
    params, next_logits = dense
    rng = np.random.RandomState(7)
    base = rng.randint(1, CFG.vocab_size, size=6).astype(np.int32)
    divergent = base.copy()
    divergent[4:] = (divergent[4:] % (CFG.vocab_size - 2)) + 1
    if np.array_equal(divergent, base):  # belt and braces
        divergent[4] = (divergent[4] % (CFG.vocab_size - 2)) + 1
    eng = ServingEngine(
        CFG, num_slots=1, max_len=16, prefill_len=8, page_size=4,
        prefill_chunk=4, prefix_cache=True,
    )
    placed = eng.place_params(params)
    # num_slots=1 serializes admissions, so B and C really see A's
    # registered pages.
    sched = eng.run(placed, [
        Request(rid="A", prompt=base, max_new_tokens=3),
        Request(rid="B", prompt=base, max_new_tokens=3),
        Request(rid="C", prompt=divergent, max_new_tokens=3),
    ])
    by_rid = {f.rid: f for f in sched.finished}
    assert by_rid["A"].tokens == _greedy(next_logits, base, 3)
    assert by_rid["B"].tokens == by_rid["A"].tokens
    assert by_rid["C"].tokens == _greedy(next_logits, divergent, 3)
    rep = sched.latency_report()
    # A missed; B full-hit (6/6 tokens); C partial-hit (page 0 = 4
    # tokens of 6).
    assert rep["prefix_cache"]["hits"] == 2
    assert rep["prefix_cache"]["misses"] == 1
    assert rep["prefix_cache"]["tokens_reused"] == 6 + 4
    # B wrote into A's registered partial page -> at least one COW
    # copy (A's own continuation writes trigger one too).
    assert rep["paged"]["cow_copies"] >= 1


def test_prefix_cache_survives_eviction_and_shares_pages(dense):
    """Cached pages outlive the slot that produced them (the cache
    holds its own pool reference), and a later identical prompt reuses
    the SAME page ids instead of re-allocating."""
    params, _ = dense
    prompt = _prompts()[1]  # 5 tokens: one full page + one partial
    eng = ServingEngine(
        CFG, num_slots=1, max_len=16, prefill_len=8, page_size=4,
        prefill_chunk=4, prefix_cache=True,
    )
    placed = eng.place_params(params)
    sched = eng.run(placed, [
        Request(rid=0, prompt=prompt, max_new_tokens=2),
        Request(rid=1, prompt=prompt, max_new_tokens=2),
    ])
    rep = sched.latency_report()
    assert rep["prefix_cache"]["hits"] == 1
    # Full page + partial page both reused: the whole 5-token prompt.
    assert rep["prefix_cache"]["tokens_reused"] == 5
    # Shared pages persisted after request 0's slot was recycled, so
    # the peak stays under two independent ingests' worth.
    assert rep["paged"]["pages_in_use_peak"] <= 4


# ----------------------------------------------- allocator/cache units


def test_page_pool_refcounts_and_reuse():
    pool = PagePool(3, page_bytes=10)
    a, b = pool.alloc(), pool.alloc()
    assert (a, b) == (0, 1)
    assert pool.pages_in_use == 2 and pool.kv_cache_bytes == 20
    pool.incref(a)
    assert not pool.decref(a)  # shared: still live
    assert pool.decref(a)      # last ref: freed
    assert pool.alloc() == 0   # lowest free, deterministic
    with pytest.raises(ValueError, match="not live"):
        pool.decref(2)
    pool.alloc()
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc()


def test_prefix_cache_match_register_evict():
    pool = PagePool(8, page_bytes=1)
    cache = PrefixCache(pool, page_size=4)
    prompt = np.arange(1, 7, dtype=np.int32)  # 6 tokens: 1 full + tail
    p0, p1 = pool.alloc(), pool.alloc()
    cache.register(prompt, [p0, p1])
    assert pool.refcount(p0) == 2 and pool.refcount(p1) == 2
    pages, covered = cache.match(prompt)
    assert pages == [p0, p1] and covered == 6
    assert cache.hits == 1 and cache.tokens_reused == 6
    # A prompt sharing only the first page matches just that page.
    other = prompt.copy()
    other[5] = 60
    pages2, covered2 = cache.match(other)
    assert pages2 == [p0] and covered2 == 4
    # Nothing matches a cold prompt.
    pages3, covered3 = cache.match(np.array([9, 9], np.int32))
    assert pages3 == [] and covered3 == 0 and cache.misses == 1
    # Release the borrower refs, put the CHAIN ROOT at the LRU front
    # (a full-prompt match touches root then partial, leaving the
    # root older), then evict: dropping the root must CASCADE to the
    # partial entry chained off it — a child whose parent is gone can
    # never match again, so it must not linger holding a pool ref.
    pages4, _ = cache.match(prompt)
    for pid in pages + pages2 + pages4:
        pool.decref(pid)
    pool.decref(p0)
    pool.decref(p1)  # the original owner's refs
    assert cache.evictable == 2
    assert cache.release_unused(1) == 2  # root evicts -> subtree goes
    assert pool.pages_in_use == 0 and len(cache) == 0
    assert cache.release_unused(1) == 0  # nothing left


def test_paged_spec_and_engine_guards(devices):
    spec = PagedKVCacheSpec(
        num_layers=2, num_slots=4, max_len=16, page_size=4,
        num_pages=16, num_heads=4, head_dim=4,
    )
    assert spec.pages_per_slot == 4
    with pytest.raises(ValueError, match="divide max_len"):
        PagedKVCacheSpec(
            num_layers=2, num_slots=4, max_len=16, page_size=5,
            num_pages=16, num_heads=4, head_dim=4,
        ).validate("replicated", None)
    with pytest.raises(ValueError, match="one full-length"):
        PagedKVCacheSpec(
            num_layers=2, num_slots=4, max_len=16, page_size=4,
            num_pages=2, num_heads=4, head_dim=4,
        ).validate("replicated", None)
    smesh = make_mesh(MeshSpec(data=1, seq=4), devices=devices[:4])
    with pytest.raises(ValueError, match="page_size"):
        PagedKVCacheSpec(
            num_layers=2, num_slots=4, max_len=16, page_size=2,
            num_pages=32, num_heads=4, head_dim=4,
        ).validate("sp", smesh)
    # Engine-level surface guards.
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingEngine(CFG, max_len=16, prefill_chunk=4)
    with pytest.raises(ValueError, match="prefix_cache"):
        ServingEngine(CFG, max_len=16, prefix_cache=True)
    with pytest.raises(ValueError, match="chunked prefill"):
        ServingEngine(
            CFG, max_len=16, page_size=4, prefix_cache=True
        )
    with pytest.raises(ValueError, match="sp"):
        ServingEngine(
            CFG, make_mesh(MeshSpec(data=1, seq=2),
                           devices=devices[:2]),
            layout="sp", max_len=16, prefill_len=8, page_size=4,
            prefill_chunk=4,
        )


# ------------------------------------------------------------ sampling


def test_sampling_greedy_default_bit_stable(dense):
    """temperature 0 == the pre-sampling argmax path, byte-identical,
    on both cache layouts."""
    params, next_logits = dense
    req = [Request(rid=0, prompt=_prompts()[0], max_new_tokens=4)]
    for kw in ({}, {"page_size": 4, "prefill_chunk": 4}):
        eng = ServingEngine(
            CFG, num_slots=2, max_len=16, prefill_len=8, **kw
        )
        placed = eng.place_params(params)
        plain = eng.run(placed, list(req))
        zero = eng.run(
            placed, list(req), sampling=SamplingConfig(temperature=0.0)
        )
        expect = _greedy(next_logits, _prompts()[0], 4)
        assert plain.finished[0].tokens == expect
        assert zero.finished[0].tokens == expect


def test_sampling_deterministic_per_slot_lane(dense):
    """A fixed (seed, trace) reproduces sampled tokens exactly, and
    different seeds diverge (the draws are really used)."""
    params, _ = dense
    eng = ServingEngine(CFG, num_slots=2, max_len=16, prefill_len=8)
    placed = eng.place_params(params)
    reqs = [
        Request(rid=i, prompt=p, max_new_tokens=6)
        for i, p in enumerate(_prompts())
    ]
    cfg = SamplingConfig(temperature=1.5, top_k=16, top_p=0.9, seed=3)
    a = eng.run(placed, list(reqs), sampling=cfg)
    b = eng.run(placed, list(reqs), sampling=cfg)
    toks = lambda s: [f.tokens for f in s.finished]  # noqa: E731
    assert toks(a) == toks(b)
    c = eng.run(
        placed, list(reqs),
        sampling=SamplingConfig(temperature=1.5, top_k=16, top_p=0.9,
                                seed=4),
    )
    assert toks(a) != toks(c)


def test_sampler_filters_and_validation():
    logits = np.array([0.0, 3.0, 2.0, 1.0, -1.0])
    # top_k=1 is greedy whatever the temperature.
    s = SlotSampler(SamplingConfig(temperature=5.0, top_k=1), 1)
    assert all(s.pick(logits, 0) == 1 for _ in range(8))
    # A tiny nucleus degenerates to greedy (argmax always survives).
    s = SlotSampler(SamplingConfig(temperature=5.0, top_p=1e-9), 1)
    assert all(s.pick(logits, 0) == 1 for _ in range(8))
    # top_k bounds the support even at high temperature.
    s = SlotSampler(SamplingConfig(temperature=50.0, top_k=3), 1)
    assert {s.pick(logits, 0) for _ in range(64)} <= {1, 2, 3}
    with pytest.raises(ValueError, match="temperature"):
        SamplingConfig(temperature=-1)
    with pytest.raises(ValueError, match="top_p"):
        SamplingConfig(temperature=1, top_p=0)
    with pytest.raises(ValueError, match="top_k"):
        SamplingConfig(temperature=1, top_k=-1)
    with pytest.raises(ValueError, match="greedy"):
        SamplingConfig(top_k=5)
