"""FSDP (ZeRO-3-style) engine tests: sharding parameters over 'data'
must be a pure memory layout — identical training math to plain DP —
while param + optimizer bytes per device scale 1/N."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
from distributed_model_parallel_tpu.parallel.data_parallel import (
    DataParallelEngine,
)
from distributed_model_parallel_tpu.parallel.fsdp import (
    FSDPEngine,
    fsdp_specs,
)
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.training.optim import SGD, AdamW


def _batch(seed=0, n=16):
    rng = np.random.RandomState(seed)
    return (
        rng.rand(n, 8, 8, 3).astype(np.float32),
        rng.randint(0, 10, size=(n,)).astype(np.int32),
    )


def _run(engine, n_steps=3, lr=0.05):
    ts = engine.init_state(jax.random.PRNGKey(0))
    x, y = engine.shard_batch(*_batch())
    losses = []
    for _ in range(n_steps):
        ts, m = engine.train_step(ts, x, y, jnp.float32(lr))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    return ts, losses


def test_fsdp_specs_policy():
    avals = {
        "big": jax.ShapeDtypeStruct((64, 33), jnp.float32),   # dim0 % 8
        "odd": jax.ShapeDtypeStruct((33, 35), jnp.float32),   # no dim % 8
        "tiny": jax.ShapeDtypeStruct((16,), jnp.float32),     # < threshold
    }
    from jax.sharding import PartitionSpec as P

    specs = fsdp_specs(avals, 8)
    assert specs["big"] == P("data", None)
    assert specs["odd"] == P()
    assert specs["tiny"] == P()


def test_fsdp_specs_no_divisible_dim_replicates_even_when_large():
    """A leaf whose every dimension resists the shard count stays
    replicated no matter how big it is — sharding must never round."""
    from jax.sharding import PartitionSpec as P

    avals = {
        "prime3d": jax.ShapeDtypeStruct((31, 37, 41), jnp.float32),
        # one divisible dim buried as the SMALLEST: still found
        "small_div": jax.ShapeDtypeStruct((8, 35, 33), jnp.float32),
    }
    specs = fsdp_specs(avals, 8)
    assert specs["prime3d"] == P()
    assert specs["small_div"] == P("data", None, None)


def test_fsdp_specs_min_shard_elems_boundary_is_inclusive():
    """prod(shape) == min_shard_elems shards; one element fewer
    replicates (the `< min_shard_elems` cut, pinned both sides)."""
    from jax.sharding import PartitionSpec as P

    avals = {
        "at": jax.ShapeDtypeStruct((32, 32), jnp.float32),    # 1024
        "under": jax.ShapeDtypeStruct((32, 31), jnp.float32),  # 992
        "scalar": jax.ShapeDtypeStruct((), jnp.float32),
    }
    specs = fsdp_specs(avals, 8, min_shard_elems=1024)
    assert specs["at"] == P(("data",), None) or specs["at"] == P(
        "data", None
    )
    assert specs["under"] == P()
    assert specs["scalar"] == P()


def test_fsdp_specs_prefers_largest_divisible_dim():
    from jax.sharding import PartitionSpec as P

    avals = {"w": jax.ShapeDtypeStruct((16, 64), jnp.float32)}
    assert fsdp_specs(avals, 8, min_shard_elems=64)["w"] == P(
        None, "data"
    )


def test_fsdp_specs_hybrid_axes_entry():
    """On a hybrid mesh the sharded dim carries the ('dcn', 'ici')
    tuple — one dim split over both fabrics."""
    from jax.sharding import PartitionSpec as P

    avals = {"w": jax.ShapeDtypeStruct((64, 3), jnp.float32)}
    specs = fsdp_specs(
        avals, 8, min_shard_elems=64, axes=("dcn", "ici")
    )
    assert specs["w"] == P(("dcn", "ici"), None)


def test_fsdp_state_shardings_follow_param_spec_for_adamw_moments():
    """AdamW's mu/nu must shard exactly like their parameters (the
    `state_shardings` protocol) — and the bias-correction step count
    stays replicated."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=8))
    eng = FSDPEngine(
        tiny_cnn(10), AdamW(), mesh, donate=False, min_shard_elems=64
    )
    sh = eng._state_sh
    flat_p = jax.tree_util.tree_leaves_with_path(sh.params)
    for moments in (sh.opt_state.mu, sh.opt_state.nu):
        flat_m = jax.tree_util.tree_leaves(moments)
        assert len(flat_m) == len(flat_p)
        for (path, psh), msh in zip(flat_p, flat_m):
            assert msh.spec == psh.spec, jax.tree_util.keystr(path)
    assert sh.opt_state.count.spec == P()
    # and at least one moment really is sharded (not all-replicated)
    assert any(
        sh_.spec != P() for sh_ in jax.tree_util.tree_leaves(
            sh.opt_state.mu
        )
    )


def test_fsdp_matches_dp_trajectory():
    mesh = make_mesh(MeshSpec(data=8))
    model = tiny_cnn(10)
    _, l_fsdp = _run(
        FSDPEngine(model, SGD(), mesh, donate=False, min_shard_elems=64)
    )
    _, l_dp = _run(DataParallelEngine(model, SGD(), mesh, donate=False))
    np.testing.assert_allclose(l_fsdp, l_dp, rtol=1e-4)


def test_fsdp_params_and_moments_physically_sharded():
    mesh = make_mesh(MeshSpec(data=8))
    eng = FSDPEngine(
        tiny_cnn(10), AdamW(), mesh, donate=False, min_shard_elems=64
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    sharded = 0
    for (path, leaf), mu in zip(
        jax.tree_util.tree_leaves_with_path(ts.params),
        jax.tree_util.tree_leaves(ts.opt_state.mu),
    ):
        if np.prod(leaf.shape) >= 64 and any(
            d % 8 == 0 for d in leaf.shape
        ):
            shard = leaf.addressable_shards[0].data
            assert np.prod(shard.shape) == np.prod(leaf.shape) // 8, (
                jax.tree_util.keystr(path)
            )
            mshard = mu.addressable_shards[0].data
            assert np.prod(mshard.shape) == np.prod(mu.shape) // 8
            sharded += 1
    assert sharded >= 3  # the conv kernels and the head


def test_fsdp_bert_with_adamw_trains():
    from distributed_model_parallel_tpu.models.bert import (
        BertConfig,
        bert_for_classification,
    )

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, max_position=16, dropout_rate=0.0,
    )
    mesh = make_mesh(MeshSpec(data=8))
    eng = FSDPEngine(
        bert_for_classification(4, cfg), AdamW(), mesh, donate=False,
        min_shard_elems=256,
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 67, size=(8, 16)).astype(np.int32)
    labels = rng.randint(0, 4, size=(8,)).astype(np.int32)
    i, l = eng.shard_batch(ids, labels)
    losses = []
    for _ in range(4):
        ts, m = eng.train_step(ts, i, l, jnp.float32(1e-3))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert losses[-1] < losses[0]
    # the embedding table is the big one: 1/8 per device
    emb = ts.params["stem"]["word"]
    assert np.prod(emb.addressable_shards[0].data.shape) == (
        np.prod(emb.shape) // 8
    )


def test_fsdp_checkpoint_roundtrip(tmp_path):
    """Sharded FSDP state saves through the host-side checkpoint and
    restores into a FRESH engine with identical continued training —
    sharding is a layout, the checkpoint is layout-independent."""
    from distributed_model_parallel_tpu.training.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    mesh = make_mesh(MeshSpec(data=8))

    def make():
        return FSDPEngine(
            tiny_cnn(10), AdamW(), mesh, donate=False, min_shard_elems=64
        )

    eng = make()
    ts = eng.init_state(jax.random.PRNGKey(0))
    x, y = eng.shard_batch(*_batch())
    for _ in range(2):
        ts, _ = eng.train_step(ts, x, y, jnp.float32(1e-3))
    save_checkpoint(str(tmp_path), ts, acc=12.5, epoch=1)

    eng2 = make()
    template = eng2.init_state(jax.random.PRNGKey(1))
    restored, acc, epoch = restore_checkpoint(str(tmp_path), template)
    assert (acc, epoch) == (12.5, 1)

    ts_a, m_a = eng.train_step(ts, x, y, jnp.float32(1e-3))
    ts_b, m_b = eng2.train_step(restored, x, y, jnp.float32(1e-3))
    np.testing.assert_allclose(
        float(m_b["loss_sum"]), float(m_a["loss_sum"]), rtol=1e-6
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(ts_a.params),
        jax.tree_util.tree_leaves(ts_b.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_fsdp_canonical_roundtrip_and_resharding(tmp_path):
    """to_canonical must produce a HOST-COMPLETE state (every leaf a full
    numpy array — the form save_checkpoint can always serialize, even
    when the runtime leaves span processes), and from_canonical must
    place it back sharded 1/N. This is the Trainer's resume path for
    sharded engines (engine.to_canonical / from_canonical)."""
    from distributed_model_parallel_tpu.training.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    mesh = make_mesh(MeshSpec(data=8))
    eng = FSDPEngine(
        tiny_cnn(10), AdamW(), mesh, donate=False, min_shard_elems=64
    )
    ts = eng.init_state(jax.random.PRNGKey(0))
    x, y = eng.shard_batch(*_batch())
    ts, _ = eng.train_step(ts, x, y, jnp.float32(1e-3))

    canon = eng.to_canonical(ts)
    for (path, leaf), runtime in zip(
        jax.tree_util.tree_leaves_with_path(canon),
        jax.tree_util.tree_leaves(ts),
    ):
        assert isinstance(leaf, np.ndarray), jax.tree_util.keystr(path)
        assert leaf.shape == runtime.shape
    save_checkpoint(str(tmp_path), canon, acc=50.0, epoch=2)

    eng2 = FSDPEngine(
        tiny_cnn(10), AdamW(), mesh, donate=False, min_shard_elems=64
    )
    template = eng2.to_canonical(eng2.init_state(jax.random.PRNGKey(3)))
    restored, acc, epoch = restore_checkpoint(str(tmp_path), template)
    assert (acc, epoch) == (50.0, 2)
    ts2 = eng2.from_canonical(restored)
    # physically sharded again: the largest leaf's addressable shard is 1/8
    big = max(
        jax.tree_util.tree_leaves(ts2.params), key=lambda l: l.size
    )
    assert np.prod(big.addressable_shards[0].data.shape) == big.size // 8
    # and training continues identically to the original state
    ts_a, m_a = eng.train_step(ts, x, y, jnp.float32(1e-3))
    ts_b, m_b = eng2.train_step(ts2, x, y, jnp.float32(1e-3))
    np.testing.assert_allclose(
        float(m_b["loss_sum"]), float(m_a["loss_sum"]), rtol=1e-6
    )
