"""Test harness: 8 virtual CPU devices so every collective path runs in CI
without hardware — the test story the reference lacks entirely (SURVEY.md §4:
no tests/ directory in the reference; its acceptance test was empirical
convergence curves, `Readme.md:283-294`).

This environment preloads a TPU PJRT plugin at interpreter start, and
backend *initialization* (which dials a remote device, slowly) is lazy.
Tests must be hermetic and CPU-only, so we force the cpu platform and the
virtual device count before any JAX computation runs. XLA_FLAGS is read
when the CPU client first initializes, so setting it here is early enough.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import re  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


# Tier-1 budget guard: experiment sweeps (experiments/) time whole training
# schedules and must only ever run under the `slow` marker. A test module
# that imports experiments/ without marking every one of its tests slow
# would silently blow the 870 s tier-1 window, so collection fails loudly.
_EXPERIMENTS_IMPORT = re.compile(
    r"^\s*(?:from|import)\s+experiments\b", re.MULTILINE
)


# Budget-rebalance convention (PR 4): a test demoted to `slow` must name
# its tier-1 twin in its docstring, so the default run's coverage story
# stays auditable. A parametrized sweep whose non-slow cases keep running
# in tier-1 is its own twin and needs no docstring note.
_TWIN_RE = re.compile(r"tier-?1|twin", re.IGNORECASE)


def pytest_collection_modifyitems(config, items):
    offenders = []
    checked = {}
    for item in items:
        path = str(getattr(item, "fspath", ""))
        if path not in checked:
            try:
                with open(path) as f:
                    checked[path] = bool(_EXPERIMENTS_IMPORT.search(f.read()))
            except OSError:
                checked[path] = False
        if checked[path] and item.get_closest_marker("slow") is None:
            offenders.append(item.nodeid)
    if offenders:
        raise pytest.UsageError(
            "tests importing experiments/ must be marked @pytest.mark.slow "
            "(tier-1 budget): " + ", ".join(sorted(offenders))
        )

    # Observability-name META-CHECK: every span/counter/metric name
    # emitted anywhere in the package (literal first argument to
    # .span/.counter/.instant/.complete/.observe/.inc/.gauge) must
    # appear in the documented registries
    # (observability/metrics.py: TRACE_EVENT_NAMES / METRIC_NAMES) —
    # an undocumented series is invisible to obsreport and to the
    # exposition surface's consumers. Pure source scan, no items
    # needed, so it runs on every collection; import is jax-free by
    # the metrics module's contract.
    from distributed_model_parallel_tpu.observability.metrics import (
        scan_emitted_names,
    )

    strays = scan_emitted_names()
    if strays:
        raise pytest.UsageError(
            "every emitted span/metric name must be documented in "
            "observability/metrics.py (TRACE_EVENT_NAMES / "
            "METRIC_NAMES): "
            + "; ".join(
                f"{name} at {', '.join(sites)}"
                for name, sites in sorted(strays.items())
            )
        )

    # Tuner-knob META-CHECK: every knob the auto-tuner's search space
    # enumerates (tuning/space.py SPACES) must correspond to a real
    # CLI flag under cli/ AND a real engine dataclass field under
    # parallel/ — a tuner searching over a phantom knob would emit
    # plans nobody can apply. Literal source scan, jax-free by the
    # space module's contract, runs on every collection.
    from distributed_model_parallel_tpu.tuning.space import (
        scan_knob_surface,
    )

    stray_knobs = scan_knob_surface()
    if stray_knobs:
        raise pytest.UsageError(
            "every tuner knob must map to a real engine/CLI "
            "parameter (tuning/space.py SPACES): "
            + "; ".join(
                f"{knob}: {', '.join(missing)}"
                for knob, missing in sorted(stray_knobs.items())
            )
        )

    # slow-twin meta-check: group collected items by test function; a
    # function whose EVERY case is slow must document its tier-1 twin.
    # Only meaningful when whole files/dirs were collected: a direct
    # node-id invocation (re-running one CI failure) can select a lone
    # slow param of a mixed sweep, which would otherwise masquerade as
    # an undocumented all-slow function and abort collection.
    if any("::" in a for a in config.args):
        return
    by_fn = {}
    for item in items:
        key = (
            str(getattr(item, "fspath", "")),
            getattr(item, "originalname", item.name),
        )
        by_fn.setdefault(key, []).append(item)
    undocumented = []
    for (path, name), group in by_fn.items():
        if any(i.get_closest_marker("slow") is None for i in group):
            continue  # mixed sweep: the non-slow cases ARE the twin
        fn = getattr(group[0], "function", None)
        doc = getattr(fn, "__doc__", None) or ""
        if not _TWIN_RE.search(doc):
            undocumented.append(f"{path}::{name}")
    if undocumented:
        raise pytest.UsageError(
            "slow-demoted tests must name their tier-1 twin in their "
            "docstring (PR 4 budget-rebalance convention): "
            + ", ".join(sorted(undocumented))
        )

    # hlolint rule-coverage meta-check: every rule in the registry must
    # be exercised by at least one positive (violation detected) AND one
    # negative (clean) test, declared via @pytest.mark.hlo_rule(id,
    # polarity). A rule nobody can trip is a rule nobody can trust; a
    # rule with no clean case may be firing on everything. The registry
    # import is jax-free (analysis/rules.py module contract). Enforced
    # only on directory-style collection (the tier-1 gate's `pytest
    # tests/`) or when the rules module itself was collected — a
    # single-OTHER-file rerun must not fail for tests it never selected;
    # directory collection still catches a deleted/emptied rules module.
    import os

    dir_collection = any(
        os.path.isdir(a.split("::")[0]) for a in config.args
    )
    rules_collected = any(
        str(getattr(i, "fspath", "")).endswith("test_hlo_rules.py")
        for i in items
    )
    if not (dir_collection or rules_collected):
        return
    from distributed_model_parallel_tpu.analysis.rules import REGISTRY

    covered = {}
    for item in items:
        for m in item.iter_markers("hlo_rule"):
            if len(m.args) != 2:
                raise pytest.UsageError(
                    f"{item.nodeid}: hlo_rule marker takes exactly "
                    f"(rule_id, polarity) as positional args, got "
                    f"{m.args!r}"
                )
            rule_id, polarity = m.args
            if rule_id not in REGISTRY:
                raise pytest.UsageError(
                    f"{item.nodeid}: hlo_rule marker names unknown rule "
                    f"{rule_id!r} (registry: {sorted(REGISTRY)})"
                )
            if polarity not in ("positive", "negative"):
                raise pytest.UsageError(
                    f"{item.nodeid}: hlo_rule polarity must be "
                    f"'positive' or 'negative', got {polarity!r}"
                )
            covered.setdefault(rule_id, set()).add(polarity)
    missing = [
        f"{rid} (missing: "
        + ", ".join(sorted({"positive", "negative"} - covered.get(rid, set())))
        + ")"
        for rid in sorted(REGISTRY)
        if covered.get(rid, set()) != {"positive", "negative"}
    ]
    if missing:
        raise pytest.UsageError(
            "every hlolint rule needs one positive and one negative "
            "test (tag with @pytest.mark.hlo_rule(id, polarity), see "
            "tests/test_hlo_rules.py): " + "; ".join(missing)
        )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)
