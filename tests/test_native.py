"""Native C++ input-path tests: the augment/normalize hot loop
(`native/augment.cpp`) must be bit-exact with the NumPy reference, and
the Loader's prefetch/worker settings must never change the data.
"""

import time

import numpy as np
import pytest

from distributed_model_parallel_tpu import native
from distributed_model_parallel_tpu.data.datasets import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    synthetic,
)
from distributed_model_parallel_tpu.data.loader import (
    Loader,
    _crop_flip_numpy,
    _draw_augment,
    normalize,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library failed to build"
)


def _images(n=64, hw=32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 256, size=(n, hw, hw, 3)).astype(np.uint8)


@pytest.mark.parametrize("workers", [1, 4])
def test_augment_normalize_bit_exact(workers):
    """C++ crop+flip+normalize == NumPy crop+flip+normalize, bitwise
    (same draws, same f32 op order), at any thread count."""
    images = _images()
    rng = np.random.RandomState(7)
    ys, xs, flips = _draw_augment(rng, len(images), 4)
    want = normalize(
        _crop_flip_numpy(images, ys, xs, flips, 4),
        CIFAR10_MEAN, CIFAR10_STD,
    ).astype(np.float32)
    got = native.augment_normalize(
        images, ys, xs, flips, 4, CIFAR10_MEAN, CIFAR10_STD,
        workers=workers,
    )
    np.testing.assert_array_equal(got, want)


def test_normalize_only_bit_exact():
    images = _images(n=16)
    want = normalize(images, CIFAR10_MEAN, CIFAR10_STD).astype(np.float32)
    got = native.normalize(images, CIFAR10_MEAN, CIFAR10_STD, workers=2)
    np.testing.assert_array_equal(got, want)


def _loader_epochs(**kw):
    ds = synthetic(num_examples=256, num_classes=4, image_size=32, seed=0)
    loader = Loader(
        ds, batch_size=32, shuffle=True, augment=True,
        mean=CIFAR10_MEAN, std=CIFAR10_STD, seed=3, **kw,
    )
    loader.set_epoch(1)
    return [(im.copy(), lb.copy()) for im, lb in loader]


def test_loader_identical_across_backends_and_workers():
    """The Loader's batches are a pure function of (seed, epoch, host,
    batch index): native vs NumPy backend, any workers/prefetch depth —
    identical streams. (This is what makes `-j` a pure throughput knob.)"""
    base = _loader_epochs(use_native=False, workers=1, prefetch=0)
    for kw in (
        dict(use_native=True, workers=1, prefetch=0),
        dict(use_native=True, workers=4, prefetch=2),
        dict(use_native=False, workers=1, prefetch=2),
    ):
        other = _loader_epochs(**kw)
        assert len(other) == len(base)
        for (im_a, lb_a), (im_b, lb_b) in zip(base, other):
            np.testing.assert_array_equal(lb_a, lb_b)
            np.testing.assert_array_equal(im_a, im_b)


def test_prefetch_propagates_worker_errors():
    """An exception inside the producer thread surfaces to the consumer
    (not a silent truncated epoch)."""

    class Broken:
        num_classes = 4

        def __len__(self):
            return 64

        def gather(self, idx):
            raise RuntimeError("disk on fire")

    loader = Loader(
        Broken(), batch_size=16, shuffle=False, prefetch=2,
        mean=CIFAR10_MEAN, std=CIFAR10_STD,
    )
    with pytest.raises(RuntimeError, match="disk on fire"):
        list(loader)


def test_native_micro_bench_reports():
    """Loader micro-bench (VERDICT r2 item 6): the native path sustains a
    real rate on this host. The floor is deliberately modest — this CI
    host is 1 core — the point is the harness exists and the number is
    reported; on a TPU host `-j` scales the pool."""
    images = _images(n=512)
    rng = np.random.RandomState(0)
    ys, xs, flips = _draw_augment(rng, len(images), 4)
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        native.augment_normalize(
            images, ys, xs, flips, 4, CIFAR10_MEAN, CIFAR10_STD, workers=1
        )
    rate = len(images) * reps / (time.perf_counter() - t0)
    print(f"\nnative augment+normalize: {rate:.0f} img/s (1 thread)")
    assert rate > 500  # 32x32 imgs; even 1 slow core clears this easily


def test_prefetch_producer_stops_on_early_abandon():
    """Abandoning the iterator mid-epoch (Trainer's --steps-per-epoch
    truncation) must stop and join the producer thread — no thread or
    staged batches may outlive the epoch."""
    import threading

    base_threads = threading.active_count()
    ds = synthetic(num_examples=512, num_classes=4, image_size=32, seed=0)
    loader = Loader(
        ds, batch_size=16, shuffle=False, augment=True,
        mean=CIFAR10_MEAN, std=CIFAR10_STD, prefetch=2,
    )
    it = iter(loader)
    next(it)
    next(it)
    it.close()  # GeneratorExit at the yield -> finally stops producer
    deadline = time.time() + 5
    while threading.active_count() > base_threads and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() == base_threads
