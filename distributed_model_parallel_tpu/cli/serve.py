"""Offline serving driver — the inference-side fourth launcher.

Feeds a synthetic request trace (random prompts, optionally staggered
Poisson arrivals collapsed to submission order — this sandbox has no
live traffic) through `serving.ServingEngine` under continuous
batching, and reports per-request latencies plus the aggregate
tokens/sec and p50/p99 per-token legs, as JSON on stdout.

  python -m distributed_model_parallel_tpu.cli.serve \
      --dim 128 --layers 4 --heads 4 --num-requests 32 \
      --num-slots 8 --max-len 256 --prefill-len 64
  python -m distributed_model_parallel_tpu.cli.serve \
      --layout tp --model-shards 4 --collective-matmul
  python -m distributed_model_parallel_tpu.cli.serve \
      --layout sp --seq-shards 4 --max-len 512

The parser carries the shared training flags (grad reduction, pipeline
stages) so a pasted training launch line fails fast with an explanation
(`cli/common.check_serving_args`) instead of silently doing nothing.
"""

from __future__ import annotations

import argparse
import json

import jax
import numpy as np

from distributed_model_parallel_tpu.cli.common import (
    add_grad_reduction_flags,
    check_serving_args,
    serve_compute_dtype,
)
from distributed_model_parallel_tpu.models.gpt import GPTConfig
from distributed_model_parallel_tpu.runtime.dist import initialize_backend
from distributed_model_parallel_tpu.runtime.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.serving.engine import ServingEngine
from distributed_model_parallel_tpu.serving.scheduler import Request


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="offline autoregressive serving (continuous "
                    "batching over a slot-paged KV cache)"
    )
    # Model (matches the lm CLI's surface; params init fresh unless
    # --checkpoint points at a trained state).
    p.add_argument("--checkpoint", default=None, metavar="DIR",
                   help="serve a TRAINED checkpoint: load the params "
                        "subtree of the newest snapshot in DIR (legacy "
                        ".npz or sharded manifest, auto-detected) "
                        "through the canonical form into the selected "
                        "layout; fails fast naming the mismatch when "
                        "the checkpoint's recorded model config "
                        "disagrees with the serve flags")
    p.add_argument("--vocab-size", default=256, type=int)
    p.add_argument("--dim", default=128, type=int)
    p.add_argument("--layers", default=4, type=int)
    p.add_argument("--heads", default=4, type=int)
    p.add_argument("--ffn-dim", default=None, type=int,
                   help="default 4*dim")
    p.add_argument("--dtype", default="float32",
                   choices=("float32", "bfloat16"),
                   help="legacy activation-dtype spelling; superseded "
                        "by --compute-dtype (bfloat16 == bf16)")
    p.add_argument("--compute-dtype", default="f32",
                   choices=("f32", "bf16", "int8"),
                   help="decode projection GEMM arithmetic "
                        "(ops/quant_matmul.py): bf16 runs the MXU's "
                        "native half path (activations + KV cache go "
                        "bf16); int8 quantizes each decode projection "
                        "with per-output-channel weight scales and "
                        "per-token activation scales, accumulating in "
                        "int32 and dequantizing on exit (activations "
                        "and cache stay f32). Prefill always runs f32")
    # Serving surface.
    p.add_argument("--layout", default="replicated",
                   choices=("replicated", "tp", "sp"),
                   help="cache/param layout: replicated; tp = heads "
                        "over 'model' (MEGATRON_RULES params); sp = "
                        "cache positions over 'seq' (online-softmax "
                        "decode, ring-attention prefill)")
    p.add_argument("--model-shards", default=1, type=int,
                   help="'model' mesh axis size (--layout tp)")
    p.add_argument("--seq-shards", default=1, type=int,
                   help="'seq' mesh axis size (--layout sp)")
    p.add_argument("--collective-matmul", action="store_true",
                   help="latency-hiding decode rings (tp layout): "
                        "opted-in projections run as chunked ppermute "
                        "rings over the slot batch — exactly "
                        "4*layers*(S-1) permutes per decode step, no "
                        "monolithic all-gather (hlolint "
                        "serve-decode-ring)")
    p.add_argument("--num-slots", default=8, type=int,
                   help="KV-cache slots = max concurrent sequences")
    p.add_argument("--max-len", default=256, type=int,
                   help="cache positions per slot (prompt + generated)")
    p.add_argument("--prefill-len", default=64, type=int,
                   help="padded prompt length (one prefill compile)")
    # Block paging (PagedAttention; serving/kv_cache.py).
    p.add_argument("--page-size", default=0, type=int,
                   help="block-paged KV cache: pool pages of this many "
                        "positions reached through a per-slot block "
                        "table — allocation scales with live tokens, "
                        "not slots*max_len; must divide --max-len "
                        "(0 = the contiguous slot layout)")
    p.add_argument("--kv-pages", default=0, type=int,
                   help="page-pool size in pages (needs --page-size; "
                        "0 = num_slots * max_len/page_size, the "
                        "no-risk worst case — smaller pools are the "
                        "memory win, bounded by live tokens)")
    p.add_argument("--prefill-chunk", default=0, type=int,
                   help="chunked prefill: ingest prompts this many "
                        "tokens per engine iteration, interleaved with "
                        "in-flight decode so a long prompt never "
                        "stalls the batch (needs --page-size; also "
                        "lifts the --prefill-len prompt cap; 0 = "
                        "monolithic prefill)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="prompt caching: share immutable prefix pages "
                        "between slots keyed on token prefix — a "
                        "repeated system prompt skips its prefill; "
                        "copy-on-write on the first divergent write "
                        "(needs --page-size and --prefill-chunk)")
    # Speculative decoding (serving/speculative.py): a small draft GPT
    # proposes k tokens per slot, the target scores all k+1 in ONE
    # verify step; acceptance is lossless (greedy output bit-identical
    # to plain decode) so these knobs are pure latency tuning.
    p.add_argument("--speculative-k", default=0, type=int,
                   help="draft tokens proposed per verify round "
                        "(0 = off; needs --page-size — rejected "
                        "suffixes roll back by truncating the block "
                        "table). Works with --prefix-cache: prefix "
                        "pages are a TARGET-side shortcut, the draft "
                        "always ingests prompts itself")
    p.add_argument("--speculative-draft", default=None, metavar="DIR",
                   help="draft model checkpoint: newest snapshot in "
                        "DIR, dims taken from its recorded config "
                        "(vocab must match the target's, recorded "
                        "max_position must cover --max-len). Omit for "
                        "a fresh-init draft sized by "
                        "--speculative-draft-layers")
    p.add_argument("--speculative-draft-layers", default=0, type=int,
                   help="layer count of the fresh-init draft when no "
                        "--speculative-draft checkpoint is given "
                        "(0 = max(1, --layers // 2); other dims "
                        "mirror the target)")
    # Synthetic arrivals: offered load instead of all-at-t=0. The
    # engine still consumes requests in submission order (this sandbox
    # has no live clock), so arrival times feed the offered-load vs
    # goodput report line, not the admission loop.
    p.add_argument("--arrival-rate", default=0.0, type=float,
                   help="Poisson arrival-EVENT rate in events/s for "
                        "the synthetic trace (0 = every request "
                        "arrives at t=0)")
    p.add_argument("--arrival-burst", default=1, type=int,
                   help="requests arriving per Poisson event (bursty "
                        "traffic: same offered load, lumpier queue; "
                        "needs --arrival-rate > 0)")
    # Decode-time sampling (serving/sampling.py; greedy default is
    # bit-stable — temperature 0 never touches an RNG).
    p.add_argument("--temperature", default=0.0, type=float,
                   help="sampling temperature (0 = greedy argmax, the "
                        "bit-stable default)")
    p.add_argument("--top-k", default=0, type=int,
                   help="keep only the k most probable tokens before "
                        "sampling (0 = no cut; needs --temperature "
                        "> 0)")
    p.add_argument("--top-p", default=1.0, type=float,
                   help="nucleus sampling: keep the smallest prefix of "
                        "probability mass reaching p (1 = no cut; "
                        "needs --temperature > 0)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="dump a Chrome trace_event JSON of the run "
                        "(per-request admission/prefill/decode spans, "
                        "per-step batch-occupancy counters — "
                        "observability/trace.py; open in "
                        "chrome://tracing or Perfetto). Fails fast if "
                        "PATH's directory does not exist.")
    from distributed_model_parallel_tpu.cli.common import (
        add_metrics_out_flag,
    )

    add_metrics_out_flag(p)
    # Synthetic trace.
    p.add_argument("--num-requests", default=16, type=int)
    p.add_argument("--prompt-len-min", default=4, type=int)
    p.add_argument("--prompt-len-max", default=32, type=int)
    p.add_argument("--max-new-tokens", default=32, type=int)
    p.add_argument("--seed", default=0, type=int)
    # Shared training flags, carried so pasted launch lines fail fast
    # with an explanation (check_serving_args) instead of an argparse
    # unknown-flag error.
    p.add_argument("--pipeline-stages", default=1, type=int,
                   help="TRAINING flag; rejected here (serving has no "
                        "stage wires)")
    add_grad_reduction_flags(p)
    return p


def synthetic_trace(args) -> list:
    """Deterministic random request set: prompt lengths uniform in
    [min, max], token ids uniform over the vocabulary (0 is reserved
    for padding)."""
    rng = np.random.RandomState(args.seed)
    out = []
    for i in range(args.num_requests):
        n = int(rng.randint(
            args.prompt_len_min, args.prompt_len_max + 1
        ))
        out.append(Request(
            rid=i,
            prompt=rng.randint(
                1, args.vocab_size, size=n
            ).astype(np.int32),
            max_new_tokens=args.max_new_tokens,
        ))
    return out


def synthetic_arrivals(args) -> np.ndarray:
    """Arrival time (seconds) per request under the --arrival-rate /
    --arrival-burst model: Poisson events (exponential inter-arrival
    gaps at the event rate), --arrival-burst requests sharing each
    event's timestamp. Deterministic in --seed (its own RNG stream, so
    adding arrival flags never perturbs the prompt content). Rate 0 is
    the legacy all-at-t=0 trace."""
    if not args.arrival_rate:
        return np.zeros(args.num_requests, np.float64)
    rng = np.random.RandomState(args.seed + 0x5EED)
    n_events = -(-args.num_requests // args.arrival_burst)  # ceil
    gaps = rng.exponential(
        1.0 / args.arrival_rate, size=n_events
    )
    events = np.cumsum(gaps)
    return np.repeat(events, args.arrival_burst)[:args.num_requests]


# GPTConfig fields recorded by the lm CLI (checkpoint_extra) -> the
# serve flag that controls each, for mismatch messages a user can act
# on. max_position is driven by --max-len (the cache length IS the
# position-table length at serve time).
_GPT_CONFIG_FLAGS = {
    "vocab_size": "--vocab-size",
    "dim": "--dim",
    "num_layers": "--layers",
    "num_heads": "--heads",
    "ffn_dim": "--ffn-dim",
    "max_position": "--max-len",
}


def _checkpoint_guard(directory: str, name: str, cfg) -> None:
    """Fail fast, naming the exact field, when the checkpoint's
    recorded model config disagrees with the serve flags — BEFORE any
    engine compiles. Checkpoints without a recorded config (e.g. saved
    by an older run) fall through to the shape guard at load time."""
    from distributed_model_parallel_tpu.checkpointing import (
        checkpoint_metadata,
    )

    try:
        meta = checkpoint_metadata(directory, name)
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    recorded = meta.get("gpt_config")
    if not recorded:
        return
    if int(recorded.get("num_experts", 0)) > 0:
        raise SystemExit(
            f"--checkpoint {directory}: the checkpoint is a "
            f"Mixture-of-Experts LM (num_experts="
            f"{recorded['num_experts']}); the serving engine builds "
            "dense decoder blocks and cannot serve it"
        )
    for field, flag in _GPT_CONFIG_FLAGS.items():
        if field not in recorded:
            continue
        want = getattr(cfg, field)
        got = recorded[field]
        if int(got) != int(want):
            raise SystemExit(
                f"--checkpoint {directory}: the checkpoint was trained "
                f"with {field}={got} but the serve flags give "
                f"{field}={want} — adjust {flag} to match the trained "
                "model"
            )


def _draft_config(args, target_cfg) -> "tuple[GPTConfig, str | None]":
    """Resolve the draft GPT's config for speculative decoding.

    With --speculative-draft, the dims come from the checkpoint's
    recorded gpt_config (the PR-8 checkpoint_extra record) — a draft is
    a DIFFERENT model, so no serve flag describes it; compatibility
    with the target (same vocabulary, position table covering
    --max-len) is checked here, before any engine compiles. Without a
    checkpoint, the draft is a fresh-init layers-truncated twin of the
    target. Returns (config, checkpoint name or None)."""
    if not args.speculative_draft:
        import dataclasses

        layers = args.speculative_draft_layers or max(
            1, args.layers // 2
        )
        return dataclasses.replace(
            target_cfg, num_layers=layers
        ), None
    from distributed_model_parallel_tpu.checkpointing import (
        checkpoint_metadata,
    )
    from distributed_model_parallel_tpu.training.checkpoint import (
        newest_checkpoint_name,
    )

    name = newest_checkpoint_name(args.speculative_draft)
    try:
        meta = checkpoint_metadata(args.speculative_draft, name)
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    recorded = meta.get("gpt_config")
    if not recorded:
        raise SystemExit(
            f"--speculative-draft {args.speculative_draft}: the "
            "checkpoint has no recorded gpt_config, so the draft's "
            "dims are unknowable from flags — re-save it with a "
            "current trainer (checkpoint_extra records the config)"
        )
    if int(recorded.get("num_experts", 0)) > 0:
        raise SystemExit(
            f"--speculative-draft {args.speculative_draft}: the draft "
            f"is a Mixture-of-Experts LM (num_experts="
            f"{recorded['num_experts']}); the serving engine builds "
            "dense decoder blocks and cannot serve it"
        )
    if int(recorded["vocab_size"]) != target_cfg.vocab_size:
        raise SystemExit(
            f"--speculative-draft {args.speculative_draft}: draft "
            f"vocab_size {recorded['vocab_size']} != target "
            f"vocab_size {target_cfg.vocab_size} — speculative "
            "acceptance compares the two models' distributions over "
            "the SAME vocabulary"
        )
    if int(recorded["max_position"]) < args.max_len:
        raise SystemExit(
            f"--speculative-draft {args.speculative_draft}: draft "
            f"max_position {recorded['max_position']} < --max-len "
            f"{args.max_len} — the draft cache mirrors the target's "
            "positions, so its position table must cover them"
        )
    return GPTConfig(
        vocab_size=int(recorded["vocab_size"]),
        dim=int(recorded["dim"]),
        num_layers=int(recorded["num_layers"]),
        num_heads=int(recorded["num_heads"]),
        ffn_dim=int(recorded["ffn_dim"]),
        max_position=int(recorded["max_position"]),
        dropout_rate=0.0,
        pad_token_id=0,
    ), name


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)
    check_serving_args(args)
    from distributed_model_parallel_tpu.cli.common import (
        setup_metrics_out,
    )

    setup_metrics_out(args.metrics_out)  # fail fast on a bad directory
    if args.trace_out:
        # Fail BEFORE any engine compiles: a mistyped directory must
        # not surface as a lost trace after the whole run.
        import os

        trace_dir = os.path.dirname(os.path.abspath(args.trace_out))
        if not os.path.isdir(trace_dir):
            raise SystemExit(
                f"--trace-out {args.trace_out}: directory "
                f"{trace_dir} does not exist"
            )
    if args.prompt_len_min < 1 or args.prompt_len_max < args.prompt_len_min:
        raise SystemExit(
            f"--prompt-len-min/max must satisfy 1 <= min <= max, got "
            f"[{args.prompt_len_min}, {args.prompt_len_max}]"
        )
    # Chunked prefill ingests in place, so only the cache caps prompt
    # length; monolithic prefill pads to one --prefill-len compile.
    prompt_cap = (
        args.max_len - 1 if args.prefill_chunk else args.prefill_len
    )
    if args.prompt_len_max > prompt_cap:
        raise SystemExit(
            f"--prompt-len-max {args.prompt_len_max} exceeds "
            + (f"--max-len - 1 = {prompt_cap}" if args.prefill_chunk
               else f"--prefill-len {prompt_cap}")
        )
    initialize_backend()
    cfg = GPTConfig(
        vocab_size=args.vocab_size,
        dim=args.dim,
        num_layers=args.layers,
        num_heads=args.heads,
        ffn_dim=args.ffn_dim or 4 * args.dim,
        max_position=args.max_len,
        dropout_rate=0.0,
        pad_token_id=0,
    )
    ckpt_name = None
    if args.checkpoint:
        # THE resume-preference rule, shared with the Trainer: serving
        # must load the same snapshot a resumed training run would.
        from distributed_model_parallel_tpu.training.checkpoint import (
            newest_checkpoint_name,
        )

        ckpt_name = newest_checkpoint_name(args.checkpoint)
        _checkpoint_guard(args.checkpoint, ckpt_name, cfg)
    shards = max(args.model_shards, args.seq_shards)
    mesh = None
    if args.layout != "replicated":
        devices = jax.devices()
        if shards > len(devices):
            raise SystemExit(
                f"{shards} shards requested but only {len(devices)} "
                "devices present"
            )
        mesh = make_mesh(
            MeshSpec(
                data=1,
                model=args.model_shards,
                seq=args.seq_shards,
            ),
            devices=devices[:shards],
        )
    engine = ServingEngine(
        cfg, mesh,
        layout=args.layout,
        num_slots=args.num_slots,
        max_len=args.max_len,
        prefill_len=args.prefill_len,
        collective_matmul=args.collective_matmul,
        compute_dtype=serve_compute_dtype(args),
        page_size=args.page_size or None,
        num_pages=args.kv_pages or None,
        prefill_chunk=args.prefill_chunk or None,
        prefix_cache=args.prefix_cache,
        speculative_k=args.speculative_k,
    )
    draft_engine = draft_params = None
    if args.speculative_k:
        draft_cfg, draft_ckpt = _draft_config(args, cfg)
        # The draft mirrors every target layout knob (speculative.py's
        # check_draft_engine enforces the cache-shape ones) EXCEPT
        # prefix_cache: prefix pages are a target-side shortcut — the
        # draft always ingests prompts itself.
        draft_engine = ServingEngine(
            draft_cfg, mesh,
            layout=args.layout,
            num_slots=args.num_slots,
            max_len=args.max_len,
            prefill_len=args.prefill_len,
            collective_matmul=args.collective_matmul,
            compute_dtype=serve_compute_dtype(args),
            page_size=args.page_size or None,
            num_pages=args.kv_pages or None,
            prefill_chunk=args.prefill_chunk or None,
        )
        if draft_ckpt is not None:
            import jax.numpy as jnp

            from distributed_model_parallel_tpu.checkpointing import (
                restore_subtree,
            )

            key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
            d_aval, _ = jax.eval_shape(
                draft_engine._full.init, key_aval
            )
            try:
                draft_raw, _ = restore_subtree(
                    args.speculative_draft, d_aval, name=draft_ckpt,
                )
            except (FileNotFoundError, KeyError, ValueError) as e:
                raise SystemExit(
                    f"--speculative-draft {args.speculative_draft}: {e}"
                )
            draft_params = draft_engine.place_params(draft_raw)
            if jax.process_index() == 0:
                print(
                    f"==> speculative draft "
                    f"{args.speculative_draft} ({draft_ckpt}, "
                    f"{draft_cfg.num_layers} layers, k="
                    f"{args.speculative_k})",
                    flush=True,
                )
        else:
            # Fresh-init draft: a real deployment trains/distills one;
            # this keeps the full speculative path exercisable from
            # the CLI with no checkpoint on disk.
            draft_params = draft_engine.init_params(
                jax.random.PRNGKey(args.seed + 1)
            )
    if args.checkpoint:
        import jax.numpy as jnp

        from distributed_model_parallel_tpu.checkpointing import (
            restore_subtree,
        )

        # The trained TrainState's `params` subtree, reassembled to the
        # canonical (host-complete) form from either on-disk layout,
        # then placed into THIS engine's replicated/TP/SP layout — the
        # same dense-twin pytree every training engine produces.
        key_aval = jax.ShapeDtypeStruct((2,), jnp.uint32)
        p_aval, _ = jax.eval_shape(engine._full.init, key_aval)
        try:
            raw, meta = restore_subtree(
                args.checkpoint, p_aval, name=ckpt_name,
            )
        except (FileNotFoundError, KeyError, ValueError) as e:
            # Shape-level guard for checkpoints with no recorded
            # config: still fails fast, naming the offending leaf.
            raise SystemExit(
                f"--checkpoint {args.checkpoint}: {e}"
            )
        params = engine.place_params(raw)
        if jax.process_index() == 0:
            print(
                f"==> serving checkpoint {args.checkpoint} "
                f"({ckpt_name}, epoch {meta.get('epoch')}, "
                f"format {meta.get('format')})",
                flush=True,
            )
    else:
        params = engine.init_params(jax.random.PRNGKey(args.seed))
    requests = synthetic_trace(args)
    if args.trace_out:
        from distributed_model_parallel_tpu.observability import trace

        trace.enable()
    sampling = None
    if args.temperature > 0:
        from distributed_model_parallel_tpu.serving.sampling import (
            SamplingConfig,
        )

        sampling = SamplingConfig(
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, seed=args.seed,
        )
    sched = engine.run(
        params, requests, sampling=sampling,
        draft=draft_engine, draft_params=draft_params,
    )
    report = sched.latency_report()
    arrivals = synthetic_arrivals(args)
    if args.arrival_rate:
        # Offered load vs achieved goodput: how much decode the trace
        # ASKED for per second vs the useful fraction of slot-steps
        # the engine actually ran. span = last arrival + one mean
        # inter-event gap (the last burst still wants its tokens), so
        # offered load stays finite even for a single burst.
        span = float(arrivals[-1]) + 1.0 / args.arrival_rate
        offered_req_s = args.num_requests / span
        report["offered_load"] = {
            "arrival_rate": args.arrival_rate,
            "arrival_burst": args.arrival_burst,
            "offered_req_per_s": round(offered_req_s, 3),
            "offered_tokens_per_s": round(
                offered_req_s * args.max_new_tokens, 3
            ),
            "goodput": report.get("goodput"),
            "achieved_tokens_per_s": report.get("tokens_per_s"),
        }
        if jax.process_index() == 0:
            print(
                f"==> offered load "
                f"{report['offered_load']['offered_tokens_per_s']} "
                f"tok/s ({args.arrival_rate} ev/s x "
                f"{args.arrival_burst}/burst) vs achieved "
                f"{report.get('tokens_per_s')} tok/s, goodput "
                f"{report.get('goodput')}",
                flush=True,
            )
    if args.metrics_out:
        from distributed_model_parallel_tpu.cli.common import (
            export_metrics_out,
        )

        export_metrics_out(args.metrics_out)
    if args.trace_out and jax.process_index() == 0:
        from distributed_model_parallel_tpu.observability import trace

        trace.get_tracer().export(args.trace_out)
        print(f"==> wrote Chrome trace to {args.trace_out}",
              flush=True)
    per_request = [
        {
            "rid": f.rid,
            "prompt_len": f.prompt_len,
            "generated": len(f.tokens),
            # The greedy token ids themselves: what a trained
            # --checkpoint run is judged by (parity vs an in-process
            # restore is pinned in tests/test_cli.py).
            "tokens": [int(t) for t in f.tokens],
            "prefill_ms": round(f.prefill_s * 1e3, 3),
            "total_ms": round(f.total_s * 1e3, 3),
        }
        for f in sched.finished
    ]
    out = {
        "serving": {
            "layout": args.layout,
            "checkpoint": args.checkpoint,
            "shards": shards,
            "collective_matmul": args.collective_matmul,
            "num_slots": args.num_slots,
            "max_len": args.max_len,
            "prefill_len": args.prefill_len,
            "page_size": args.page_size or None,
            "prefill_chunk": args.prefill_chunk or None,
            "prefix_cache": args.prefix_cache,
            "temperature": args.temperature,
            "speculative_k": args.speculative_k or None,
            "speculative_draft": args.speculative_draft,
            **report,
        },
        "requests": per_request,
    }
    if jax.process_index() == 0:
        print(json.dumps(out, indent=2))
    return out


if __name__ == "__main__":
    main()
