"""Finetune demonstration + the reference's third figure.

The reference finetunes pretrained MobileNetV2 weights onto CIFAR-10 and
publishes accuracy vs batch size (96.3% @ bs128; `Readme.md:200-209`,
`pic/image-20220123200738642.png`). Its pretrained torch checkpoint is
not in this sandbox, so this experiment produces one END TO END through
the framework's own torch bridge:

1. PRETRAIN MobileNetV2 on the texture-family task
   (`SyntheticTextures` — genuine generalization structure) and export
   the weights in the reference's exact checkpoint schema
   (`{'net': module.* state_dict}`, `torch_import.save_reference_checkpoint`).
2. FINETUNE from that .pth onto the DIFFERENT class-mean task
   (`Synthetic`) at several batch sizes via the CLI's `--finetune` flag
   — the reference's workflow, format and entry point.
3. Plot best val acc vs batch size -> pic/finetune_acc_vs_batch.png,
   the counterpart of the reference's third figure. A from-scratch
   control at the reference's headline batch shows what the transplant
   buys.

Run (real chip; ~8-12 min): python experiments/finetune_sweep.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCHES = (64, 128, 256, 512)
PRETRAIN_EPOCHS = 3
FINETUNE_EPOCHS = 4
LR_PRETRAIN = 0.05
LR_FINETUNE = 0.02


def main():
    import jax

    from distributed_model_parallel_tpu.cli import data_parallel

    workdir = os.path.join(REPO, "experiments", "finetune_work")
    os.makedirs(workdir, exist_ok=True)
    os.chdir(workdir)
    ckpt_path = os.path.join(workdir, "pretrained_mnv2.pth")

    # ---- 1. pretrain on textures + export reference-format .pth ------
    if not os.path.exists(ckpt_path):
        print("== pretraining on SyntheticTextures ==", flush=True)
        import shutil

        shutil.rmtree("checkpoint", ignore_errors=True)
        data_parallel.main([
            "-type", "SyntheticTextures", "--model", "mobilenetv2",
            "--dtype", "bfloat16", "-b", "512", "--val-batch-size", "1000",
            "--epochs", str(PRETRAIN_EPOCHS), "--lr", str(LR_PRETRAIN),
            "--device-cache", "--steps-per-dispatch", "16",
            "--log-file", "pretrain.txt",
        ])
        # Rebuild the trainer state from the best checkpoint and export.
        import numpy as np

        from distributed_model_parallel_tpu.models.mobilenetv2 import (
            mobilenet_v2,
        )
        from distributed_model_parallel_tpu.models.torch_import import (
            save_reference_checkpoint,
        )
        from distributed_model_parallel_tpu.training.checkpoint import (
            restore_checkpoint,
        )
        from distributed_model_parallel_tpu.parallel.data_parallel import (
            TrainState,
        )
        from distributed_model_parallel_tpu.training.optim import SGD

        model = mobilenet_v2(10)
        params, state = model.init(jax.random.PRNGKey(0))
        opt = SGD(momentum=0.9, weight_decay=1e-4)
        template = TrainState(
            params, state, opt.init(params), np.zeros((), np.int32)
        )
        restored, acc, epoch = restore_checkpoint("checkpoint", template)
        save_reference_checkpoint(
            ckpt_path, restored.params, restored.model_state,
            acc=acc, epoch=epoch,
        )
        print(f"exported {ckpt_path} (pretrain val acc {acc:.2f})",
              flush=True)

    # ---- 2. finetune sweep on the class-mean task --------------------
    results = []
    for bs in BATCHES:
        print(f"== finetune bs={bs} ==", flush=True)
        import shutil

        shutil.rmtree("checkpoint", ignore_errors=True)
        out = data_parallel.main([
            "-type", "Synthetic", "--model", "mobilenetv2",
            "--dtype", "bfloat16", "-b", str(bs),
            "--val-batch-size", "512",
            "--epochs", str(FINETUNE_EPOCHS),
            "--lr", str(LR_FINETUNE * bs / 128),  # linear-scaled lr
            "--finetune", ckpt_path,
            "--log-file", f"finetune_{bs}.txt",
        ])
        results.append({"batch": bs, "best_acc": out["best_acc"]})
        print(results[-1], flush=True)

    # from-scratch control at the reference's headline batch
    import shutil

    shutil.rmtree("checkpoint", ignore_errors=True)
    scratch = data_parallel.main([
        "-type", "Synthetic", "--model", "mobilenetv2",
        "--dtype", "bfloat16", "-b", "128", "--val-batch-size", "512",
        "--epochs", str(FINETUNE_EPOCHS), "--lr", str(LR_FINETUNE),
        "--log-file", "scratch_128.txt",
    ])

    # ---- 3. the third figure -----------------------------------------
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = [r["batch"] for r in results]
    ys = [r["best_acc"] for r in results]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(xs, ys, marker="o", label="finetune (texture-pretrained)")
    ax.axhline(scratch["best_acc"], ls="--", color="gray",
               label=f"from scratch @bs128 ({scratch['best_acc']:.1f}%)")
    ax.set_xscale("log", base=2)
    ax.set_xticks(xs)
    ax.set_xticklabels([str(x) for x in xs])
    ax.set_xlabel("finetune batch size")
    ax.set_ylabel("best val acc (%)")
    ax.set_title(
        f"MobileNetV2 finetune: acc vs batch "
        f"({FINETUNE_EPOCHS} epochs, lr scaled with batch)"
    )
    ax.legend()
    fig.tight_layout()
    pic = os.path.join(REPO, "pic", "finetune_acc_vs_batch.png")
    fig.savefig(pic, dpi=120)
    out_json = os.path.join(REPO, "experiments", "finetune_sweep.json")
    with open(out_json, "w") as f:
        json.dump({
            "pretrain_epochs": PRETRAIN_EPOCHS,
            "finetune_epochs": FINETUNE_EPOCHS,
            "finetune": results,
            "scratch_bs128": scratch["best_acc"],
        }, f, indent=1)
    print(f"wrote {pic} and {out_json}", flush=True)


if __name__ == "__main__":
    main()
