"""Observability subsystem (INTERNALS.md §13): the span tracer's
nesting/export contract against a committed Chrome-trace golden file
(deterministic clock injected — no wall time in any assertion), the
static cost engine's closed-form predictions pinned for hand-computed
combos, the costgate's regression/missing-row/tolerance semantics as
pure-function tests, and a Trainer-phase-timing smoke on the virtual
mesh."""

import json
import os

import numpy as np
import pytest

from distributed_model_parallel_tpu.observability import (
    cost,
    metrics,
    trace,
)
from distributed_model_parallel_tpu.observability.costgate import (
    gate_check,
    make_ledger,
)

GOLDEN = os.path.join(
    os.path.dirname(__file__), "golden", "chrome_trace.json"
)


class FakeClock:
    """Deterministic injected clock: 1.0, 2.0, 3.0, ... seconds."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        self.t += 1.0
        return self.t


def build_golden_tracer() -> trace.Tracer:
    """The exact event sequence the committed golden file pins (also
    invoked by the generator that wrote the golden)."""
    t = trace.Tracer(clock=FakeClock(), enabled=True)
    with t.span("epoch", epoch=0):
        with t.span("step", n=2):
            pass
        t.counter("batch_occupancy", 3)
    t.instant("evict", slot=1)
    tid = t.track_id("request 'r0'")
    t.complete("prefill", 10.0, 12.5, tid=tid, prompt_len=4)
    return t


# ------------------------------------------------------------- tracer


def test_span_nesting_and_chrome_export_golden(tmp_path):
    tracer = build_golden_tracer()
    path = tracer.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        got = json.load(f)  # acceptance: round-trips json.loads
    with open(GOLDEN) as f:
        want = json.load(f)
    assert got == want

    # Structural nesting, independent of the golden bytes: the inner
    # span's [ts, ts+dur) interval is contained in the outer's, on the
    # same track — how Chrome complete events nest.
    spans = {
        e["name"]: e for e in got["traceEvents"] if e["ph"] == "X"
    }
    outer, inner = spans["epoch"], spans["step"]
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    # The named request track is disjoint from thread tracks and its
    # complete event carries the caller-supplied timestamps.
    assert spans["prefill"]["tid"] >= 1000
    assert spans["prefill"]["dur"] == pytest.approx(2.5e6)


def test_disabled_tracer_is_single_branch_noop():
    tracer = trace.Tracer(enabled=False)
    s1 = tracer.span("a", x=1)
    s2 = tracer.span("b")
    assert s1 is s2  # the shared singleton: no per-call allocation
    with s1:
        tracer.counter("c", 1)
        tracer.instant("i")
        tracer.complete("d", 0.0, 1.0)
    assert len(tracer) == 0


def test_tracer_thread_safety_and_thread_tracks():
    import threading

    tracer = trace.Tracer(enabled=True)

    def work():
        for _ in range(50):
            with tracer.span("w"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    with tracer.span("main"):
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    events = tracer.to_chrome()["traceEvents"]
    assert len(events) == 4 * 50 + 1
    # Each thread got its own small-ordinal track.
    assert {e["tid"] for e in events} <= set(range(5))


# -------------------------------------------------------- cost engine


def test_cost_flat_ring_hand_computed():
    # 100 MB over a flat 64-ring, 161 unfused ops (the scaling64 §3a
    # shape): beta = 2*63/64 * 100e6/100e9 = 1.96875 ms; alpha =
    # 161 * 2*63 * 1us = 20.286 ms.
    got = cost.ring_all_reduce_s(100e6, 64, n_ops=161)
    assert got == pytest.approx(0.00196875 + 0.020286, rel=1e-12)
    # Bucketed (one fused op) keeps the beta, drops alpha to one ring.
    got = cost.ring_all_reduce_s(100e6, 64, n_ops=1)
    assert got == pytest.approx(0.00196875 + 0.000126, rel=1e-12)


def test_cost_hierarchical_two_level_hand_computed():
    # 100 MB over 2 x 32 dcn x ici, 4 buckets: ici beta 2*31/32 *
    # 100e6/100e9 = 1.9375 ms; dcn beta 2*(1/2) * (100e6/32)/25e9 =
    # 0.125 ms; alpha 4 * (2*31*1us + 2*1*10us) = 0.328 ms.
    got = cost.two_level_all_reduce_s(100e6, 32, 2, n_buckets=4)
    assert got == pytest.approx(
        0.0019375 + 0.000125 + 0.000328, rel=1e-12
    )


def test_cost_int8_wire_hand_computed():
    # Same combo on the int8 wire: the dcn leg quarters (0.03125 ms)
    # and each of the 4 buckets pays one extra sidecar hop pair per
    # payload hop: alpha = 4 * (2*31*1us + 2*2*1*10us) = 0.408 ms.
    got = cost.two_level_all_reduce_s(
        100e6, 32, 2, n_buckets=4, wire="int8"
    )
    assert got == pytest.approx(
        0.0019375 + 0.00003125 + 0.000408, rel=1e-12
    )


def test_cost_moe_exchange_flat_vs_hierarchical():
    # The §3c MoE shape: 12.5M bf16 elements over 2 x 32. The
    # hierarchical exchange drops (K-1)*I = 32 dcn hops to 1 and keeps
    # the dcn bytes equal — so it must be strictly cheaper.
    elems = 12_500_000
    flat = cost.flat_all_to_all_s(elems, 2, 32, 2)
    hier = cost.hierarchical_all_to_all_s(elems, 2, 32, 2)
    assert hier < flat
    # int8 wire quarters only the dcn leg of the bf16 payload.
    hier_int8 = cost.hierarchical_all_to_all_s(
        elems, 2, 32, 2, wire="int8"
    )
    dcn_leg = (1 / 2) * elems * 2 / cost.BW_DCN_EFFECTIVE
    assert hier - hier_int8 == pytest.approx(dcn_leg / 2, rel=1e-9)


def test_cost_plan_bubble_factor_hand_computed():
    """The scheduled-plan bubble (ISSUE 20): (VM+pp-1)/(VM) with V
    only counting for the interleaved schedule and M defaulting to
    pp*V — so gpipe/1f1b twins at one M share a bubble and the
    interleaved twin's is strictly smaller; pp=1 has no bubble."""
    assert cost.plan_bubble_factor(1) == 1.0
    assert cost.plan_bubble_factor(2) == pytest.approx(1.5)  # M=pp
    assert cost.plan_bubble_factor(2, "gpipe", 1, 4) \
        == pytest.approx(1.25)
    assert cost.plan_bubble_factor(2, "1f1b", 1, 4) \
        == pytest.approx(1.25)
    assert cost.plan_bubble_factor(2, "interleaved", 2, 4) \
        == pytest.approx(1.125)
    # default M = pp*V for interleaved: (pp*V*V... ) = (8+1)/8
    assert cost.plan_bubble_factor(2, "interleaved", 2) \
        == pytest.approx(1.125)


def test_cost_composed_plan_step_schedule_terms():
    """`composed_plan_step_s` stays byte-stable for pre-ISSUE-20
    callers (gpipe defaults price the old M+pp-1 wire ticks) and the
    scheduled closed form honestly prices MORE wire ticks
    (2MV + 2(pp-1)) while the compute term folds the bubble — the
    cross-schedule win lives in the lowered tier where comm is
    schedule-symmetric."""
    args = (2, 1, 4, 1_000_000, 4, 128, 64, 1000, 8, 8, 1)
    base = cost.composed_plan_step_s(*args)
    assert base == cost.composed_plan_step_s(
        *args, schedule="gpipe", virtual_stages=1,
        num_microbatches=0, compute_s=0.0,
    )
    sched = cost.composed_plan_step_s(
        *args, schedule="1f1b", num_microbatches=4,
    )
    assert sched > cost.composed_plan_step_s(*args, num_microbatches=4)
    # the compute fold is compute_s * bubble, additively
    with_c = cost.composed_plan_step_s(
        *args, schedule="1f1b", num_microbatches=4, compute_s=1.0,
    )
    assert with_c - sched == pytest.approx(
        cost.plan_bubble_factor(2, "1f1b", 1, 4), rel=1e-9,
    )


def test_predict_collectives_walker_hand_computed():
    """The HLO walker's per-kind pricing on a hand-built module: one
    ring hop within 'ici', one all-reduce crossing 'dcn'."""
    from distributed_model_parallel_tpu.analysis.collectives import (
        MeshModel,
        classify_instruction,
    )
    from distributed_model_parallel_tpu.analysis.hlo import (
        Buffer,
        Instruction,
    )

    mesh = MeshModel(
        axis_names=("dcn", "ici"),
        shape=(2, 4),
        coords={
            i: (i // 4, i % 4) for i in range(8)
        },
    )
    hop = Instruction(
        name="cp.1", op="collective-permute",
        buffers=(Buffer("f32", (1024,)),), refs=frozenset(),
        op_name="", computation="main",
        source_target_pairs=((0, 1), (1, 2), (2, 3), (3, 0)),
    )
    ar = Instruction(
        name="ar.1", op="all-reduce",
        buffers=(Buffer("f32", (256,)),), refs=frozenset(),
        op_name="", computation="main",
        replica_groups=((0, 4), (1, 5), (2, 6), (3, 7)),
    )
    cols = [
        classify_instruction(hop, mesh),
        classify_instruction(ar, mesh),
    ]
    out = cost.predict_collectives(cols, mesh, dcn_axis="dcn")
    # hop: 4096 B within {ici} -> alpha 1us, beta 4096/100e9.
    # ar: 1024 B across {dcn} (group 2) -> alpha 2*1*10us, beta
    #     2*(1/2)*1024/25e9.
    assert out.n_collectives == 2
    assert out.alpha_s == pytest.approx(1e-6 + 2e-5, rel=1e-12)
    assert out.beta_s == pytest.approx(
        4096 / 100e9 + 1024 / 25e9, rel=1e-12
    )
    assert out.bytes_by_fabric == {"ici": 4096, "dcn": 1024}


def test_combo_cost_row_shape():
    """One cheap op-level combo through the real lower+classify+predict
    path (the costgate pre-gate's unit of work)."""
    from distributed_model_parallel_tpu.analysis.lint import Combo

    row = cost.combo_cost(Combo("cm_ag", 2))
    assert row["predicted_step_s"] > 0
    assert row["n_collectives"] >= 1
    assert set(row) >= {
        "predicted_step_s", "alpha_s", "beta_s", "n_collectives",
        "bytes_by_fabric",
    }


# ----------------------------------------------------------- costgate


def _ledger(rows):
    return make_ledger(rows, tolerance=0.05)


def test_costgate_regression_detected_and_named():
    ledger = _ledger({"ddp/S4/bucketed": {"predicted_step_s": 1e-3}})
    fails = gate_check(
        ledger, {"ddp/S4/bucketed": {"predicted_step_s": 1.2e-3}}
    )
    assert len(fails) == 1
    assert "ddp/S4/bucketed" in fails[0]
    assert "regressed" in fails[0]


def test_costgate_tolerance_boundary():
    ledger = _ledger({"x": {"predicted_step_s": 1e-3}})
    # Within tolerance (exactly +5%) passes; just past it fails.
    assert gate_check(ledger, {"x": {"predicted_step_s": 1.05e-3}}) \
        == []
    assert gate_check(ledger, {"x": {"predicted_step_s": 1.06e-3}})
    # Improvements always pass.
    assert gate_check(ledger, {"x": {"predicted_step_s": 0.5e-3}}) \
        == []


def test_costgate_missing_row_fails_for_new_combo():
    ledger = _ledger({"x": {"predicted_step_s": 1e-3}})
    fails = gate_check(
        ledger,
        {"x": {"predicted_step_s": 1e-3},
         "new/S2": {"predicted_step_s": 1e-3}},
    )
    assert len(fails) == 1 and "new/S2" in fails[0] \
        and "no ledger row" in fails[0]
    # The pre-gate's name check catches combos that were not lowered.
    fails = gate_check(
        ledger, {"x": {"predicted_step_s": 1e-3}},
        require_rows_for=["x", "unlowered/S8"],
    )
    assert len(fails) == 1 and "unlowered/S8" in fails[0]


def test_costgate_subset_update_refuses_drifted_constants(tmp_path):
    """A --filter/--pregate --update onto a ledger priced under
    different constants must refuse BEFORE lowering anything: merging
    would keep the un-lowered rows at the old physics while stamping
    the file with the new constants."""
    from distributed_model_parallel_tpu.observability import costgate

    ledger = _ledger({"x": {"predicted_step_s": 1e-3}})
    ledger["constants"]["alpha_hop_s"] = 123.0
    path = tmp_path / "ledger.json"
    path.write_text(json.dumps(ledger))
    rc = costgate.main([
        "--update", "--filter", "cm_ag/S2", "--ledger", str(path),
    ])
    assert rc == 2
    # The refusal left the drifted ledger untouched.
    assert json.loads(path.read_text()) == ledger


def test_costgate_constants_drift_fails():
    ledger = _ledger({"x": {"predicted_step_s": 1e-3}})
    ledger["constants"]["alpha_hop_s"] = 2e-6
    fails = gate_check(ledger, {"x": {"predicted_step_s": 1e-3}})
    assert len(fails) == 1 and "alpha_hop_s" in fails[0]


def test_committed_ledger_covers_the_full_matrix():
    """The acceptance pin: experiments/cost_ledger.json carries a row
    for EVERY combo in the hlolint matrix, under the current
    constants."""
    from distributed_model_parallel_tpu.analysis.lint import full_matrix
    from distributed_model_parallel_tpu.observability.costgate import (
        DEFAULT_LEDGER,
        load_ledger,
    )

    ledger = load_ledger(DEFAULT_LEDGER)
    assert gate_check(
        ledger, {}, require_rows_for=[c.name for c in full_matrix()]
    ) == []


# ------------------------------------------- trainer + serving smokes


def test_trainer_phase_spans_smoke(tmp_path, devices):
    """Trainer phase timing on the virtual mesh: one tiny epoch with a
    sharded async checkpoint must leave fetch/step/sync spans plus the
    checkpoint-blocked / snapshot / background-write trio."""
    import jax

    from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DataParallelEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec,
        make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD
    from distributed_model_parallel_tpu.training.trainer import (
        Trainer,
        TrainerConfig,
    )

    tracer = trace.Tracer(enabled=True)
    trace.set_tracer(tracer)
    reg = metrics.MetricsRegistry(enabled=True)
    metrics.set_metrics(reg)
    try:
        mesh = make_mesh(MeshSpec(data=2), devices=devices[:2])
        engine = DataParallelEngine(tiny_cnn(10), SGD(), mesh)
        rng = np.random.RandomState(0)
        batches = [
            (
                rng.rand(8, 8, 8, 3).astype(np.float32),
                rng.randint(0, 10, 8).astype(np.int32),
            )
            for _ in range(2)
        ]
        cfg = TrainerConfig(
            epochs=1, print_freq=1, save_best=False, save_last=True,
            checkpoint_format="sharded", async_save=True,
            checkpoint_dir=str(tmp_path), log_dir=str(tmp_path),
        )
        trainer = Trainer(engine, batches, None, cfg,
                          rng=jax.random.PRNGKey(0))
        trainer.fit()
        names = {
            e["name"] for e in tracer.to_chrome()["traceEvents"]
        }
        assert {
            "fetch", "step", "sync", "checkpoint_blocked",
            "ckpt_snapshot", "ckpt_background_write",
        } <= names
        # The metrics registry mirrors the phases as distributions
        # (tentpole wiring: step-time / fetch / checkpoint-blocked
        # histograms plus the checkpoint writer pair).
        exported = reg.to_json()
        assert {
            "train_fetch_s", "train_step_s",
            "train_checkpoint_blocked_s", "ckpt_snapshot_s",
            "ckpt_background_write_s",
        } <= set(exported["histograms"])
        assert reg.histogram("train_step_s").count == 2
        assert exported["counters"]["train_batches_total"] == 2
        # And the REAL CPU-mesh trace renders through obsreport: the
        # attribution covers the trainer+checkpoint phases, the
        # residual is finite, and the measured-vs-predicted row keys
        # on a live ledger combo (acceptance: the report pipeline
        # works on an actual run, not just the canned golden).
        from distributed_model_parallel_tpu.observability import (
            attribution,
            report,
        )
        from distributed_model_parallel_tpu.observability.costgate import (
            DEFAULT_LEDGER,
            load_ledger,
        )

        chrome = tracer.to_chrome()
        attr = attribution.attribute(chrome)
        assert {"fetch", "step", "sync", "checkpoint_blocked"} <= {
            p.name for p in attr.phases
        }
        assert 0.0 <= attr.residual_share < 1.0
        rendered = report.render_report(
            chrome, metrics=exported, ledger=load_ledger(DEFAULT_LEDGER),
            combos=["ddp/S4/dcn2/bucketed"],
        )
        assert "unattributed residual" in rendered
        assert "ddp/S4/dcn2/bucketed" in rendered
        assert "train_step_s" in rendered
    finally:
        trace.set_tracer(None)
        metrics.set_metrics(None)


def test_serving_telemetry_and_request_spans(devices):
    """Scheduler telemetry: goodput / mean occupancy in the report and
    the per-request queued/prefill/decode spans plus the per-step
    occupancy counter in the trace."""
    import jax

    from distributed_model_parallel_tpu.models.gpt import GPTConfig
    from distributed_model_parallel_tpu.serving.engine import (
        ServingEngine,
    )
    from distributed_model_parallel_tpu.serving.scheduler import (
        Request,
    )

    tracer = trace.Tracer(enabled=True)
    trace.set_tracer(tracer)
    reg = metrics.MetricsRegistry(enabled=True)
    metrics.set_metrics(reg)
    try:
        cfg = GPTConfig(
            vocab_size=32, dim=16, num_layers=1, num_heads=2,
            ffn_dim=32, max_position=16, dropout_rate=0.0,
        )
        eng = ServingEngine(
            cfg, None, layout="replicated", num_slots=2, max_len=16,
            prefill_len=4,
        )
        params = eng.init_params(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        reqs = [
            Request(rid=i, prompt=rng.randint(1, 32, size=3),
                    max_new_tokens=3)
            for i in range(3)
        ]
        sched = eng.run(params, reqs)
        rep = sched.latency_report()
        assert rep["requests"] == 3
        assert rep["decode_steps"] == len(sched.step_occupancy) > 0
        assert 0 < rep["mean_batch_occupancy"] <= 2
        assert 0 < rep["goodput"] <= 1
        # goodput IS occupancy over capacity (each active slot yields
        # one token per step).
        assert rep["goodput"] == pytest.approx(
            rep["mean_batch_occupancy"] / 2, abs=1e-3
        )
        events = tracer.to_chrome()["traceEvents"]
        names = {e["name"] for e in events}
        assert {
            "prefill", "decode_step", "queued", "decode",
            "batch_occupancy",
        } <= names
        # One queued+prefill+decode trio per finished request, each on
        # its own named track.
        assert sum(1 for e in events if e["name"] == "queued") == 3
        assert len({
            e["tid"] for e in events if e["name"] == "queued"
        }) == 3
        # Serving metrics wiring: per-request histograms through the
        # scheduler, per-call histograms through the engine, goodput /
        # occupancy as gauges, generated tokens as a counter.
        exported = reg.to_json()
        assert {
            "serve_queued_s", "serve_ttft_s", "serve_token_s",
            "serve_prefill_s", "serve_decode_step_s",
        } <= set(exported["histograms"])
        assert exported["histograms"]["serve_ttft_s"]["count"] == 3
        assert exported["histograms"]["serve_token_s"]["count"] == sum(
            len(f.tokens) - 1 for f in sched.finished
        )
        assert exported["gauges"]["serve_goodput"] == rep["goodput"]
        assert exported["counters"]["serve_tokens_total"] == sum(
            len(f.tokens) for f in sched.finished
        ) == rep["generated_tokens"]
    finally:
        trace.set_tracer(None)
        metrics.set_metrics(None)


def test_scheduler_request_spans_coherent_under_injected_clock():
    """The scheduler takes its lifecycle timestamps from the TRACER's
    clock (Tracer.now), so an injected clock yields a coherent trace:
    span ts/dur follow the fake clock exactly, never wall time."""
    from distributed_model_parallel_tpu.serving.scheduler import (
        Request,
        Scheduler,
    )

    clock = FakeClock()
    tracer = trace.Tracer(clock=clock, enabled=True)  # origin = 1.0
    trace.set_tracer(tracer)
    try:
        sched = Scheduler(num_slots=1, max_len=8)
        sched.submit(Request(rid="r", prompt=np.array([1, 2]),
                             max_new_tokens=1))          # t_submit 2.0
        seq = sched.admit()                              # t_admit 3.0
        seq.t_first_token = tracer.now()                 # 4.0
        seq.generated.append(7)
        sched.finish(seq.slot)                           # eviction 5.0
        spans = {
            e["name"]: e
            for e in tracer.to_chrome()["traceEvents"]
            if e["ph"] == "X"
        }
        assert spans["queued"]["ts"] == pytest.approx(1e6)   # 2.0-1.0
        assert spans["queued"]["dur"] == pytest.approx(1e6)
        assert spans["prefill"]["dur"] == pytest.approx(1e6)
        assert spans["decode"]["dur"] == pytest.approx(1e6)
        fin = sched.finished[0]
        assert fin.prefill_s == pytest.approx(2.0)  # submit->first tok
        assert fin.total_s == pytest.approx(3.0)
    finally:
        trace.set_tracer(None)


def test_serve_cli_trace_out_missing_dir_fails_fast():
    """--trace-out with a nonexistent directory exits BEFORE any
    engine compiles, naming the directory."""
    from distributed_model_parallel_tpu.cli import serve

    with pytest.raises(SystemExit) as exc:
        serve.main([
            "--trace-out", "/no/such/dir/anywhere/trace.json",
            "--num-requests", "1",
        ])
    assert "does not exist" in str(exc.value)


def test_serve_cli_metrics_out_missing_dir_fails_fast():
    """--metrics-out shares --trace-out's fail-fast contract: a
    mistyped directory must not surface as a lost export after the
    whole run."""
    from distributed_model_parallel_tpu.cli import serve

    with pytest.raises(SystemExit) as exc:
        serve.main([
            "--metrics-out", "/no/such/dir/anywhere/metrics.json",
            "--num-requests", "1",
        ])
    assert "does not exist" in str(exc.value)


def test_progress_print_never_measures_its_own_readback_stall(
    monkeypatch, devices,
):
    """The RESULTS §2 fence fix, regression-pinned with an injected
    slow clock: every `jax.device_get` of the JUST-dispatched group's
    metrics advances the fake clock by 10 s (the readback stall of
    fencing in-flight compute). Because the progress print reads the
    PREVIOUS group's metrics through the one-deep snapshot seam — and
    the step-time sample closes BEFORE the print's fetch — at most the
    first print's no-predecessor fallback can land a stall in the
    train_step_s histogram. The pre-fix loop (fetching the current
    group at every print) puts one in every window after the first."""
    import jax

    from distributed_model_parallel_tpu.models.tinycnn import tiny_cnn
    from distributed_model_parallel_tpu.parallel.data_parallel import (
        DataParallelEngine,
    )
    from distributed_model_parallel_tpu.runtime.mesh import (
        MeshSpec,
        make_mesh,
    )
    from distributed_model_parallel_tpu.training.optim import SGD
    from distributed_model_parallel_tpu.training.trainer import (
        Trainer,
        TrainerConfig,
    )

    class TickClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            self.t += 1e-3
            return self.t

    clock = TickClock()
    trace.set_tracer(trace.Tracer(clock=clock))  # tracing stays OFF
    reg = metrics.MetricsRegistry(enabled=True)
    metrics.set_metrics(reg)
    try:
        mesh = make_mesh(MeshSpec(data=2), devices=devices[:2])
        engine = DataParallelEngine(tiny_cnn(10), SGD(), mesh)
        rng = np.random.RandomState(0)
        batches = [
            (
                rng.rand(8, 8, 8, 3).astype(np.float32),
                rng.randint(0, 10, 8).astype(np.int32),
            )
            for _ in range(4)
        ]
        cfg = TrainerConfig(
            epochs=1, print_freq=1, save_best=False,
        )
        trainer = Trainer(engine, batches, None, cfg,
                          rng=jax.random.PRNGKey(0))

        latest = []
        orig_step = engine.train_step

        def recording_step(state, *a):
            state, m = orig_step(state, *a)
            latest.append(m)
            return state, m

        monkeypatch.setattr(engine, "train_step", recording_step)
        orig_get = jax.device_get

        def slow_get(tree):
            # Fetching the newest dispatch's metrics = fencing the
            # in-flight compute: charge the injected stall. Anything
            # older already finished behind the newer dispatch.
            if latest and tree is latest[-1]:
                clock.t += 10.0
            return orig_get(tree)

        monkeypatch.setattr(jax, "device_get", slow_get)
        trainer.train_epoch(0)
        hist = reg.histogram("train_step_s")
        assert hist is not None and hist.count == 4
        samples = hist._samples
        stalled = sum(1 for s in samples if s > 5.0)
        assert stalled <= 1, (
            f"step-time histogram measured its own readback stall: "
            f"{samples}"
        )
        # And the fix costs nothing at the tail: the LAST window is
        # always stall-free.
        assert samples[-1] < 5.0
    finally:
        trace.set_tracer(None)
        metrics.set_metrics(None)
