"""Hierarchical, overlapped MoE expert dispatch — the hand-rolled
two-level token exchange that replaces the partitioner-inserted flat
all-to-all of `parallel/expert_parallel.py`'s GSPMD path.

The GSPMD MoE lowering (`models/moe.py` + `EXPERT_RULES`) leaves the
token exchange to XLA: the (E, B, C, D) dispatch buffers reshard from
batch-sharded to expert-sharded through whatever fused all-to-all the
partitioner picks, and on a factored `MeshSpec(dcn=K)` mesh that one
collective drags the full token payload across the slow cross-slice
fabric — exactly the sin `ops/grad_reduction.py` eliminated for
gradients and `ops/collective_matmul.py` for TP/SP projections. This
module re-expresses the exchange the same two ways, following the
hierarchical all-to-all of DeepSpeed-MoE (Rajbhandari et al., ICML
2022; PAPERS.md) and the GShard dense-dispatch formulation (Lepikhin et
al., ICLR 2021):

* **Two-level routing** (`dispatch_exchange` / `combine_exchange`).
  The expert-parallel world is the (factored) data fabric itself: the
  S = K·I devices each own E/S experts (linear fabric index k·I + i,
  'dcn'-major — the `data_replica_index` convention). A device's local
  dispatch buffer (E, B/S, C, D) moves in two stages, every hop a
  `moe_ring`-scoped `lax.ppermute`:

      intra-slice exchange over 'ici'   I-1 permutes, chunk = the 1/I
                                        of the buffer destined to one
                                        ici column (rides the fast
                                        fabric exclusively)
      cross-slice exchange over 'dcn'   K-1 permutes on the regrouped
                                        buffer — each message carries
                                        the 1/ici expert shard
                                        (E/I experts x the slice's
                                        tokens), so the slow fabric
                                        sees K-1 contiguous messages
                                        of |X|/K instead of the flat
                                        lowering's (K-1)*I fragments
                                        of |X|/S

  Total cross-'dcn' bytes equal the flat exchange's (tokens must
  cross); what the hierarchy buys is the alpha term — I x fewer, I x
  larger messages on the high-latency fabric — and the (I-1)/I of the
  payload that now never leaves the slice (INTERNALS.md section 11 has
  the accounting). The transpose is mirrored explicitly via
  `jax.custom_vjp`: d(dispatch_exchange) runs the combine-direction
  movement and vice versa, like the dual kernels of
  `ops/collective_matmul.py`.

* **Chunked compute overlap** (`overlapped_expert_ffn`). The exchange
  around the expert FFN decomposes into per-source-chunk ppermute
  steps, the same decomposition `ag_matmul`/`matmul_rs` use (Wang et
  al., ASPLOS 2023): on ring hop r the chunk from source i-r arrives
  and its FFN fires while the hop-(r+1) permute — and the hop-r return
  permute carrying finished outputs home — are already in flight.
  Neither permute depends on the resident chunk's dots, so the
  scheduler hides the exchange behind the MXU. Hop count is identical
  to the unfused path (2(I-1) + 2(K-1) tagged permutes per exchange
  pair), only the dependency structure changes — which is what the
  hlolint rule `moe-hierarchical-a2a` pins.

Consumed through two policies (mirroring `CollectiveMatmul` /
`LocalCollectiveMatmul`), threaded to `models/moe.py` via
`Context.expert_dispatch`:

* `ExpertDispatch` — the jit-level policy for
  `ExpertParallelEngine(dispatch="hierarchical")`: the MoE FFN runs as
  a shard_map region over the data axes whose in/out specs match the
  engine's at-rest layout (expert weights sharded 1/S on their leading
  E axis over `data_axis_names(mesh)` — the EP memory win, kept), so
  region entry is free.
* `LocalExpertDispatch` — the shard_map-level policy for the DDP
  engines (already inside one big shard_map over the data axes):
  weights stay replicated in storage (checkpoints interoperate), each
  shard slices its E/S expert block by fabric index; the slice
  transpose scatters the block gradient into the full-shape cotangent,
  which the engine's bucketed/monolithic data-axis reduction
  reassembles — composing with `grad_reduction="overlapped"`'s
  stagewise VJP and its per-stage `moe_aux` cotangent channel.

Parity: hierarchical (and overlapped) == GSPMD flat == single-device
dense at rtol 1e-5, forward + grads + trajectories, dropped-token cases
included (tests/test_expert_dispatch.py) — the exchange is a pure
permutation of the dispatch buffers, so the math is the dense layer's
bit for bit up to batching order.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_model_parallel_tpu.models.moe import expert_ffn
from distributed_model_parallel_tpu.ops.collective_matmul import _axis_size
from distributed_model_parallel_tpu.ops.wire_codec import (
    coded_ppermute,
    require_dcn_axis,
)
from distributed_model_parallel_tpu.runtime.compat import shard_map

# The named scope every exchange hop carries; hlolint's
# `moe-hierarchical-a2a` counts `\bmoe_ring\b`-scoped collective-permutes
# (word-matched so the transpose spelling `transpose(moe_ring)` still
# counts and a future `moe_ring2` scope cannot inherit the pin).
SCOPE = "moe_ring"


def _tagged_ppermute(x, axis_name, perm):
    with jax.named_scope(SCOPE):
        return lax.ppermute(x, axis_name, perm)


def _wire_ppermute(x, axis_name, perm, wire):
    """One cross-slice hop, payload in the wire dtype when the step
    opted into `dcn_compression` (`ops/wire_codec.coded_ppermute`): the
    chunk is encoded, permuted under the nested `moe_ring`/`dcn_wire`
    scopes (so BOTH the exchange-chain pin and the byte-aware wire pin
    see it), and decoded on arrival; the int8 scale sidecar rides the
    same permutation under its own `dcn_scale` scope, outside the
    moe_ring count. With `wire="none"` this is `_tagged_ppermute`."""
    if wire == "none":
        return _tagged_ppermute(x, axis_name, perm)
    return coded_ppermute(x, axis_name, tuple(perm), wire, tag=SCOPE)


def _check_dcn_wire(wire: str, dcn_axis) -> str:
    return require_dcn_axis(wire, dcn_axis, what="MoE exchange")


def _fabric_size(ici_axis, dcn_axis) -> int:
    return _axis_size(ici_axis) * (
        _axis_size(dcn_axis) if dcn_axis is not None else 1
    )


def _check_experts(e: int, s: int) -> int:
    if e % s:
        raise ValueError(
            f"expert dispatch: num_experts ({e}) must be divisible by "
            f"the expert-parallel fabric size ({s}) — each device owns "
            "an E/S expert block"
        )
    return e // s


# ------------------------------------------------- pairwise exchange
# The primitive both levels ride: an all-to-all over ONE axis expressed
# as size-1 permutes. Chunk j of the leading axis is addressed to the
# device at axis coordinate j; the result's leading axis is indexed by
# SOURCE coordinate. Self-transpose and an involution (sending chunks
# back returns them home), which is what makes the combine path the
# exact mirror of the dispatch path.


def _a2a_chunks(x, axis_name, wire: str = "none"):
    """(G, ...) dest-indexed -> (G, ...) source-indexed over `axis_name`
    (G = axis size), as G-1 `moe_ring`-scoped ppermutes — hop r moves
    every device's chunk for the destination r steps around. `wire`
    compresses each hop's payload (`ops/wire_codec.py`); the engines
    set it only on the 'dcn' stage — the intra-slice stage always rides
    the math dtype."""
    size = _axis_size(axis_name)
    if x.shape[0] != size:
        raise ValueError(
            f"_a2a_chunks: leading axis {x.shape[0]} != axis "
            f"{axis_name!r} size {size}"
        )
    if size == 1:
        return x
    i = lax.axis_index(axis_name)

    def chunk(c):
        return lax.dynamic_slice_in_dim(x, c % size, 1, axis=0)

    out = jnp.zeros_like(x)
    out = lax.dynamic_update_slice_in_dim(out, chunk(i), i, axis=0)
    for r in range(1, size):
        perm = [(j, (j + r) % size) for j in range(size)]
        recv = _wire_ppermute(chunk(i + r), axis_name, perm, wire)
        out = lax.dynamic_update_slice_in_dim(
            out, recv, (i - r) % size, axis=0
        )
    return out


# --------------------------------------------- two-level movement ops


def _dispatch_impl(xin, ici_axis, dcn_axis, wire="none"):
    """(E, b, C, D) dest-expert-major local buffer -> (E/S, S*b, C, D):
    this device's expert block's inputs from EVERY source, source order
    = linear fabric index ('dcn'-major, matching the batch sharding).
    `wire` compresses ONLY the cross-slice stage's payload."""
    n_i = _axis_size(ici_axis)
    n_k = _axis_size(dcn_axis) if dcn_axis is not None else 1
    e, b, c, d = xin.shape
    s = n_i * n_k
    el = _check_experts(e, s)
    x = xin.reshape(n_k, n_i, el, b, c, d)
    # Stage 1 — intra-slice: chunk by destination ici column.
    x = jnp.swapaxes(x, 0, 1)          # (I_dest, K_dest, el, b, c, d)
    x = _a2a_chunks(x, ici_axis)       # (I_src,  K_dest, el, b, c, d)
    x = jnp.swapaxes(x, 0, 1)          # (K_dest, I_src,  el, b, c, d)
    # Stage 2 — cross-slice: ONE exchange over 'dcn' on the regrouped
    # buffer (each chunk already carries the 1/ici expert shard) — the
    # only stage the wire codec touches.
    if dcn_axis is not None:
        x = _a2a_chunks(x, dcn_axis, wire)  # (K_src, I_src, el, b, c, d)
    x = jnp.moveaxis(x, 2, 0)          # (el, K_src, I_src, b, c, d)
    return x.reshape(el, s * b, c, d)


def _combine_impl(y, ici_axis, dcn_axis, wire="none"):
    """Inverse of `_dispatch_impl`: (E/S, S*b, C, D) expert outputs back
    to (E, b, C, D) dest-expert-major at each token's home shard."""
    n_i = _axis_size(ici_axis)
    n_k = _axis_size(dcn_axis) if dcn_axis is not None else 1
    el, sb, c, d = y.shape
    s = n_i * n_k
    if sb % s:
        raise ValueError(
            f"combine: gathered batch {sb} not divisible by fabric {s}"
        )
    b = sb // s
    x = y.reshape(el, n_k, n_i, b, c, d)
    x = jnp.moveaxis(x, 0, 2)          # (K_src, I_src, el, b, c, d)
    if dcn_axis is not None:
        # The pairwise exchange is an involution: applying it again
        # returns every chunk to its origin.
        x = _a2a_chunks(x, dcn_axis, wire)  # (K_dest, I_src, el, b, c, d)
    x = jnp.swapaxes(x, 0, 1)          # (I_src, K_dest, el, b, c, d)
    x = _a2a_chunks(x, ici_axis)       # (I_dest, K_dest, el, b, c, d)
    x = jnp.swapaxes(x, 0, 1)          # (K, I, el, b, c, d)
    return x.reshape(el * s, b, c, d)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def dispatch_exchange(xin, ici_axis, dcn_axis, wire="none"):
    """Two-level token dispatch: (E, b, C, D) -> (E/S, S*b, C, D).
    Backward runs the mirrored combine-direction movement (custom_vjp)
    over the SAME wire dtype, so no flat collective — and no silent
    f32 fallback — appears in either direction."""
    return _dispatch_impl(xin, ici_axis, dcn_axis, wire)


def _dispatch_fwd(xin, ici_axis, dcn_axis, wire):
    return _dispatch_impl(xin, ici_axis, dcn_axis, wire), None


def _dispatch_bwd(ici_axis, dcn_axis, wire, _, dy):
    return (_combine_impl(dy, ici_axis, dcn_axis, wire),)


dispatch_exchange.defvjp(_dispatch_fwd, _dispatch_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def combine_exchange(y, ici_axis, dcn_axis, wire="none"):
    """Two-level expert-output return: (E/S, S*b, C, D) -> (E, b, C, D).
    Backward runs the mirrored dispatch-direction movement."""
    return _combine_impl(y, ici_axis, dcn_axis, wire)


def _combine_fwd(y, ici_axis, dcn_axis, wire):
    return _combine_impl(y, ici_axis, dcn_axis, wire), None


def _combine_bwd(ici_axis, dcn_axis, wire, _, dy):
    return (_dispatch_impl(dy, ici_axis, dcn_axis, wire),)


combine_exchange.defvjp(_combine_fwd, _combine_bwd)


def flat_expert_exchange(xin, axis_names):
    """The monolithic baseline the two-level path replaces: ONE fused
    `lax.all_to_all` over the joint fabric — the shape the GSPMD
    partitioner picks, full token payload across every axis in
    `axis_names` at once. Kept for the parity tests and the
    `--moe-microbench` flat column."""
    return lax.all_to_all(
        xin, axis_names, split_axis=0, concat_axis=1, tiled=True
    )


def flat_expert_return(y, axis_names):
    """Inverse of `flat_expert_exchange`."""
    return lax.all_to_all(
        y, axis_names, split_axis=1, concat_axis=0, tiled=True
    )


# -------------------------------------------------- overlapped kernel


def _chunk_ffn(ffn, ch):
    """Run the expert FFN on one ring chunk. `ch` is (1, el, b, C, D)
    (flat ring) or (1, I, el, b, C, D) (regrouped dcn ring); the FFN
    consumes expert-major (el, rows, C, D)."""
    if ch.ndim == 5:
        y = ffn(ch[0])
        return y[None]
    _, n_i, el, b, c, d = ch.shape
    z = jnp.moveaxis(ch[0], 1, 0).reshape(el, n_i * b, c, d)
    y = ffn(z).reshape(el, n_i, b, c, d)
    return jnp.moveaxis(y, 0, 1)[None]


def _ffn_ring(z, ffn, axis_name, wire="none"):
    """The latency-hiding loop: z (G, ...) dest-indexed chunks; each hop
    r delivers the chunk from source i-r, whose FFN fires while the
    hop-(r+1) permute and the hop-r return permute are in flight (the
    dots depend on neither — the same argument as `_ring_fold`).
    Returns (G, ...) with slot g holding the FFN output of this shard's
    chunk g, back home. `wire` compresses BOTH directions of each hop
    (the engines set it only when the ring runs over 'dcn')."""
    size = _axis_size(axis_name)
    i = lax.axis_index(axis_name)

    def chunk(c):
        return lax.dynamic_slice_in_dim(z, c % size, 1, axis=0)

    out = jnp.zeros_like(z)
    out = lax.dynamic_update_slice_in_dim(
        out, _chunk_ffn(ffn, chunk(i)), i, axis=0
    )
    for r in range(1, size):
        fwd = [(j, (j + r) % size) for j in range(size)]
        bwd = [(j, (j - r) % size) for j in range(size)]
        recv = _wire_ppermute(chunk(i + r), axis_name, fwd, wire)
        y_r = _chunk_ffn(ffn, recv)
        back = _wire_ppermute(y_r, axis_name, bwd, wire)
        out = lax.dynamic_update_slice_in_dim(
            out, back, (i + r) % size, axis=0
        )
    return out


def overlapped_expert_ffn(xin, ffn, ici_axis, dcn_axis, wire="none"):
    """Fused exchange + expert FFN + return with chunked overlap:
    expert compute on chunk k overlaps communication of chunk k+1.

    Flat fabric: the ring runs over the single axis (S chunks). Hybrid:
    the intra-slice regroup runs first (I-1 permutes), then the ring
    over 'dcn' (K chunks, each the 1/ici-regrouped shard) so the SLOW
    hops are the hidden ones, then the inverse regroup. Same tagged hop
    count as the unfused path — only the dependency structure differs.
    Backward is jax's transpose of the loop: per-chunk FFN VJPs on the
    reversed permutes, chunked like the forward."""
    n_i = _axis_size(ici_axis)
    n_k = _axis_size(dcn_axis) if dcn_axis is not None else 1
    e, b, c, d = xin.shape
    el = _check_experts(e, n_i * n_k)
    if dcn_axis is None:
        z = xin.reshape(n_i, el, b, c, d)
        out = _ffn_ring(z, ffn, ici_axis)
        return out.reshape(e, b, c, d)
    x = xin.reshape(n_k, n_i, el, b, c, d)
    x = jnp.swapaxes(x, 0, 1)          # (I_dest, K_dest, el, b, c, d)
    x = _a2a_chunks(x, ici_axis)       # (I_src,  K_dest, el, b, c, d)
    z = jnp.swapaxes(x, 0, 1)          # (K_dest, I_src,  el, b, c, d)
    out = _ffn_ring(z, ffn, dcn_axis, wire)  # (K_dest, I_src, el, ...)
    out = jnp.swapaxes(out, 0, 1)      # (I_src,  K_dest, el, b, c, d)
    out = _a2a_chunks(out, ici_axis)   # (I_dest, K_dest, el, b, c, d)
    out = jnp.swapaxes(out, 0, 1)      # (K, I, el, b, c, d)
    return out.reshape(e, b, c, d)


def exchanged_expert_ffn(xin, ffn, ici_axis, dcn_axis, overlap,
                         wire="none"):
    """One MoE layer's exchange+FFN+return on local buffers: the
    unfused two-level path (dispatch -> one big FFN -> combine) or the
    chunked overlapped kernel. Both carry exactly
    2(I-1) + 2(K-1) `moe_ring` permutes forward (and the same again in
    the transposed backward) whatever the wire dtype — compression
    changes the payload bytes of the 'dcn' hops, never the hop
    structure."""
    if overlap:
        return overlapped_expert_ffn(xin, ffn, ici_axis, dcn_axis, wire)
    z = dispatch_exchange(xin, ici_axis, dcn_axis, wire)
    y = ffn(z)
    return combine_exchange(y, ici_axis, dcn_axis, wire)


def exchange_permutes(ici_size: int, dcn_size: int = 1) -> int:
    """Tagged `moe_ring` permute count of ONE forward exchange pair
    (dispatch + combine, fused or not): 2(I-1) + 2(K-1). A train step
    doubles it (the backward mirrors hop for hop) — the exact count
    hlolint's `moe-hierarchical-a2a` pins."""
    return 2 * (ici_size - 1) + 2 * (dcn_size - 1)


# ------------------------------------------------------------ policies


def _moe_local(h, dispatch, combine, w, *, ici_axis, dcn_axis, overlap,
               wire="none"):
    """Per-shard MoE FFN around the exchange: local one-hot pack, the
    two-level (optionally overlapped) exchange+FFN, local weighted
    unpack. `w` leaves are this shard's E/S expert block."""
    xin = jnp.einsum("btec,btd->ebcd", dispatch, h)
    ffn = partial(expert_ffn, w, dtype=h.dtype)
    y = exchanged_expert_ffn(xin, ffn, ici_axis, dcn_axis, overlap, wire)
    return jnp.einsum("btec,ebcd->btd", combine, y)


@dataclasses.dataclass(frozen=True)
class ExpertDispatch:
    """jit-level policy for `ExpertParallelEngine(dispatch=
    "hierarchical")`: the MoE FFN becomes a shard_map region over the
    (factored) data axes. In/out specs match the engine's at-rest
    layout — tokens batch-sharded, expert weights 1/S on their leading
    E axis over `data_axis_names(mesh)` — so region entry never costs a
    collective. Routing stays OUTSIDE the region under GSPMD: it is
    per-sample math, identical shard-local and global."""

    mesh: Mesh
    overlap: bool = False
    # Compress the cross-slice hops of the exchange to this wire dtype
    # ("none" | "bf16" | "int8", `ops/wire_codec.py`); requires the
    # mesh to carry a 'dcn' factor.
    dcn_compression: str = "none"

    def __call__(self, h, dispatch, combine, w):
        from distributed_model_parallel_tpu.runtime.mesh import (
            data_hierarchy_axes,
        )

        d_axes, ici_axis, dcn_axis = data_hierarchy_axes(self.mesh)
        _check_dcn_wire(self.dcn_compression, dcn_axis)
        s = int(math.prod(self.mesh.shape[a] for a in d_axes))
        _check_experts(w["w_in"].shape[0], s)
        if h.shape[0] % s:
            raise ValueError(
                f"hierarchical dispatch: batch {h.shape[0]} must be "
                f"divisible by the expert-parallel fabric size ({s})"
            )
        dd = tuple(d_axes)
        wspec = {
            "w_in": P(dd, None, None),
            "b_in": P(dd, None),
            "w_out": P(dd, None, None),
            "b_out": P(dd, None),
        }
        fn = shard_map(
            partial(
                _moe_local, ici_axis=ici_axis, dcn_axis=dcn_axis,
                overlap=self.overlap, wire=self.dcn_compression,
            ),
            mesh=self.mesh,
            in_specs=(
                P(dd, None, None),
                P(dd, None, None, None),
                P(dd, None, None, None),
                wspec,
            ),
            out_specs=P(dd, None, None),
            check_vma=False,
        )
        return fn(h, dispatch, combine, w)


@dataclasses.dataclass(frozen=True)
class LocalExpertDispatch:
    """shard_map-level policy for the DDP engines (already inside one
    shard_map over the data axes): weights stay REPLICATED in storage
    (checkpoints and the dense init interoperate); each shard slices
    its E/S expert block by fabric index. The slice transpose scatters
    the block's gradient into the full-shape cotangent, and the
    engine's data-axis gradient reduction (monolithic pmean, bucketed
    rings, or the overlapped stagewise firing) reassembles the
    block-disjoint pieces into exactly the replicated-dense gradient —
    which is how hierarchical dispatch composes with
    `grad_reduction="overlapped"` and its per-stage `moe_aux`
    cotangent channel."""

    ici_axis: str
    dcn_axis: Optional[str] = None
    overlap: bool = False
    # Cross-slice wire dtype (see ExpertDispatch.dcn_compression).
    dcn_compression: str = "none"

    def __call__(self, h, dispatch, combine, w):
        _check_dcn_wire(self.dcn_compression, self.dcn_axis)
        s = _fabric_size(self.ici_axis, self.dcn_axis)
        el = _check_experts(w["w_in"].shape[0], s)
        idx = lax.axis_index(self.ici_axis)
        if self.dcn_axis is not None:
            idx = (
                lax.axis_index(self.dcn_axis) * _axis_size(self.ici_axis)
                + idx
            )
        del el
        w_loc = {
            k: lax.dynamic_slice_in_dim(
                v, idx * (v.shape[0] // s), v.shape[0] // s, axis=0
            )
            for k, v in w.items()
        }
        return _moe_local(
            h, dispatch, combine, w_loc,
            ici_axis=self.ici_axis, dcn_axis=self.dcn_axis,
            overlap=self.overlap, wire=self.dcn_compression,
        )


__all__ = [
    "ExpertDispatch",
    "LocalExpertDispatch",
    "SCOPE",
    "combine_exchange",
    "dispatch_exchange",
    "exchange_permutes",
    "exchanged_expert_ffn",
    "flat_expert_exchange",
    "flat_expert_return",
    "overlapped_expert_ffn",
]
