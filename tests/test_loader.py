"""Input-pipeline tests: the DistributedSampler semantics the reference
lacks (`utils.py:21` `train_sampler=None`) — per-host disjoint shards,
identical batch counts on every host, wrap-padding for tiny datasets."""

import numpy as np

from distributed_model_parallel_tpu.data.datasets import synthetic
from distributed_model_parallel_tpu.data.loader import Loader


def _host_batches(ds, batch, P, **kw):
    return [
        list(Loader(ds, batch_size=batch, process_index=p, process_count=P,
                    shuffle=False, drop_last=False, **kw))
        for p in range(P)
    ]


def test_hosts_get_equal_batch_counts_and_disjoint_coverage():
    ds = synthetic(num_examples=64, num_classes=4, image_size=4)
    per_host = _host_batches(ds, 4, 4)
    counts = [len(b) for b in per_host]
    assert counts == [4] * 4
    # Together the hosts cover every example exactly once (n % P == 0).
    seen = np.concatenate(
        [lb for b in per_host for (_, lb) in b]
    )
    assert len(seen) == 64


def test_padding_when_dataset_smaller_than_host_count():
    # Regression: pad > len(order) used to under-pad, leaving some hosts
    # with EMPTY shards — a guaranteed multi-host collective hang.
    ds = synthetic(num_examples=2, num_classes=2, image_size=4)
    per_host = _host_batches(ds, 1, 8)
    counts = [len(b) for b in per_host]
    assert counts == [1] * 8, "every host must see the same batch count"
    for batches in per_host:
        images, labels = batches[0]
        assert images.shape[0] == 1 and labels.shape[0] == 1


def test_epoch_shuffle_is_deterministic_and_host_consistent():
    ds = synthetic(num_examples=32, num_classes=4, image_size=4)
    a = Loader(ds, batch_size=8, seed=3, process_index=0, process_count=2)
    b = Loader(ds, batch_size=8, seed=3, process_index=1, process_count=2)
    a.set_epoch(5)
    b.set_epoch(5)
    la = np.concatenate([lb for _, lb in a])
    lb_ = np.concatenate([lb for _, lb in b])
    # Same epoch permutation on both hosts => strided shards are disjoint
    # and their union is the whole (shuffled) dataset.
    assert len(la) == len(lb_) == 16
    # Determinism: re-iterating the same epoch gives identical batches.
    la2 = np.concatenate([lb for _, lb in a])
    np.testing.assert_array_equal(la, la2)


def test_device_normalize_yields_uint8_with_identical_augment_draws():
    """device_normalize ships augmented uint8; applying the device
    normalizer must reproduce the host-normalized batch bit-for-bit
    (same keyed crop/flip draws, same /255-mean/std math)."""
    import pytest

    from distributed_model_parallel_tpu.data.datasets import (
        CIFAR10_MEAN,
        CIFAR10_STD,
    )
    from distributed_model_parallel_tpu.data.loader import device_normalizer

    ds = synthetic(num_examples=64, num_classes=4, image_size=8, seed=1)
    kw = dict(batch_size=16, shuffle=True, augment=True, seed=7,
              mean=CIFAR10_MEAN, std=CIFAR10_STD, use_native=False)
    host = Loader(ds, **kw)
    dev = Loader(ds, device_normalize=True, **kw)
    tf = device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
    n = 0
    for (hb, hl), (db, dl) in zip(host, dev):
        assert db.dtype == np.uint8
        np.testing.assert_array_equal(hl, dl)
        np.testing.assert_allclose(
            np.asarray(tf(db)), hb, rtol=1e-6, atol=1e-6
        )
        n += 1
    assert n == 4

    # The native hot loop is host-side fused augment+normalize; asking
    # for both must refuse loudly, not silently normalize twice.
    with pytest.raises(ValueError, match="device_normalize"):
        Loader(ds, device_normalize=True, use_native=True,
               batch_size=16, mean=CIFAR10_MEAN, std=CIFAR10_STD)
