"""Tiny CNN — the framework's smoke/benchmark-debug model.

The reference ships a `test()` smoke function that runs a random batch
through the net and prints the shape (`code/distributed_training/model/
mobilenetv2.py:79-83`); this is that idea promoted to a first-class zoo
member: a 4-block conv net small enough to compile in seconds on the
1-core CI host, with the same stem/blocks/head structure as the real
families so every engine (DP, DDP, pipeline) and the CLI can exercise
their full wiring cheaply.
"""

from __future__ import annotations

from typing import List, Sequence

from distributed_model_parallel_tpu.models import layers as L
from distributed_model_parallel_tpu.models import staging

WIDTH = 16
N_BLOCKS = 4


def _stem() -> L.Layer:
    return L.sequential(
        L.conv2d(3, WIDTH, 3, stride=1, padding=1),
        L.batchnorm2d(WIDTH),
        L.relu(),
    )


def _block(i: int) -> L.Layer:
    stride = 2 if i == N_BLOCKS - 1 else 1
    return L.sequential(
        L.conv2d(WIDTH, WIDTH, 3, stride=stride, padding=1),
        L.batchnorm2d(WIDTH),
        L.relu(),
    )


def _head(num_classes: int) -> L.Layer:
    return L.sequential(L.global_avg_pool(), L.linear(WIDTH, num_classes))


def tiny_cnn(num_classes: int = 10, *, remat: bool = False) -> L.Layer:
    blocks = [_block(i) for i in range(N_BLOCKS)]
    if remat:
        blocks = [L.remat(b) for b in blocks]
    return staging.staged_model(_stem(), blocks, _head(num_classes))


def split_stages(num_stages: int, num_classes: int = 10, *,
                 boundaries: Sequence[int] | None = None) -> List[L.Layer]:
    blocks = [_block(i) for i in range(N_BLOCKS)]
    cuts = staging.split_points(num_stages, boundaries, len(blocks))
    return staging.assemble_stages(blocks, _stem(), _head(num_classes), cuts)


def partition_pytree(tree, num_stages: int, *,
                     boundaries: Sequence[int] | None = None) -> List[dict]:
    cuts = staging.split_points(num_stages, boundaries, N_BLOCKS)
    return staging.partition_tree(tree, cuts)
